"""Operator-controllable capabilities: allow/deny lists gating what queries
and clients may do.

Role of the reference's Capabilities system (reference:
core/src/dbs/capabilities.rs — Targets<T> None/Some/All, FuncTarget,
NetTarget, MethodTarget, RouteTarget; a capability allows an element iff the
allow-list matches it AND the deny-list does not). Carried by the Datastore
(server-wide policy, configured from CLI/env) and consulted at the chokepoints:
builtin-function dispatch (fnc), scripting, guest access (HTTP + RPC), RPC
method dispatch, HTTP route dispatch, and outbound network targets
(http:: functions).
"""

from __future__ import annotations

import ipaddress
from typing import FrozenSet, Iterable, Optional, Union

from surrealdb_tpu.err import SurrealError


# ------------------------------------------------------------------ targets
class FuncTarget:
    """`family` (whole namespace), `family::*`, or `family::name`
    (reference capabilities.rs FuncTarget)."""

    __slots__ = ("family", "name")

    def __init__(self, family: str, name: Optional[str] = None):
        self.family = family
        self.name = name

    @staticmethod
    def parse(s: str) -> "FuncTarget":
        # lowercased: fnc.run lowercases call names before matching
        s = s.strip().lower()
        if not s:
            raise SurrealError("empty function target")
        if "::" in s:
            family, rest = s.split("::", 1)
            if rest in ("*", ""):
                return FuncTarget(family)
            return FuncTarget(family, rest)
        return FuncTarget(s)

    def matches(self, func_name: str) -> bool:
        if self.name is not None:
            if "::" not in func_name:
                return False
            f, r = func_name.split("::", 1)
            return f == self.family and r == self.name
        f = func_name.split("::", 1)[0]
        return f == self.family

    def __repr__(self):
        return f"{self.family}::{self.name}" if self.name else f"{self.family}::*"

    def __eq__(self, o):
        return isinstance(o, FuncTarget) and (self.family, self.name) == (o.family, o.name)

    def __hash__(self):
        return hash((self.family, self.name))


class NetTarget:
    """Host name, IP, or CIDR block, each with an optional port
    (reference capabilities.rs NetTarget)."""

    __slots__ = ("host", "net", "port")

    def __init__(self, host: Optional[str], net, port: Optional[int]):
        self.host = host  # lowercase hostname, or None
        self.net = net  # ipaddress.ip_network, or None
        self.port = port

    @staticmethod
    def parse(s: str) -> "NetTarget":
        s = s.strip()
        if not s:
            raise SurrealError("empty network target")
        host, port = s, None
        try:
            if s.startswith("["):  # [v6]:port
                body, _, rest = s[1:].partition("]")
                host = body
                if rest.startswith(":"):
                    port = int(rest[1:])
            elif s.count(":") == 1 and "/" not in s:
                host, p = s.split(":")
                port = int(p)
        except ValueError as e:
            raise SurrealError(f"invalid network target {s!r}") from e
        try:
            net = ipaddress.ip_network(host, strict=False)
            return NetTarget(None, net, port)
        except ValueError:
            return NetTarget(host.lower(), None, port)

    def matches(self, host: str, port: Optional[int] = None) -> bool:
        if self.port is not None and port != self.port:
            return False
        if self.net is not None:
            try:
                return ipaddress.ip_address(host) in self.net
            except ValueError:
                return False
        return host.lower() == self.host

    def __repr__(self):
        base = str(self.net) if self.net is not None else self.host
        return f"{base}:{self.port}" if self.port is not None else base

    def __eq__(self, o):
        return isinstance(o, NetTarget) and (self.host, self.net, self.port) == (
            o.host,
            o.net,
            o.port,
        )

    def __hash__(self):
        return hash((self.host, self.net, self.port))


RPC_METHODS = frozenset(
    {
        "ping", "info", "use", "signup", "signin", "authenticate", "invalidate",
        "reset", "kill", "live", "let", "set", "unset", "select", "insert",
        "create", "upsert", "update", "merge", "patch", "relate", "delete",
        "version", "query", "run", "graphql", "ml_import", "ml_export",
    }
)

HTTP_ROUTES = frozenset(
    {
        "export", "import", "rpc", "version", "sql", "signin", "signup", "key",
        "ml", "graphql", "health", "sync", "status", "metrics", "slow",
        "trace", "traces", "debug", "cluster", "events", "statements", "tenants",
        "advisor",
    }
)


def _check_member(kind: str, value: str, universe: FrozenSet[str]) -> str:
    v = value.strip().lower()
    if v not in universe:
        raise SurrealError(f"invalid {kind} target {value!r}")
    return v


# ------------------------------------------------------------------ Targets
class Targets:
    """None / Some(set) / All (reference capabilities.rs Targets<T>)."""

    __slots__ = ("kind", "items")

    def __init__(self, kind: str, items=None):
        self.kind = kind  # "none" | "some" | "all"
        self.items = items or ()

    NONE: "Targets"
    ALL: "Targets"

    @staticmethod
    def some(items: Iterable) -> "Targets":
        return Targets("some", tuple(items))

    def matches(self, *elem) -> bool:
        if self.kind == "none":
            return False
        if self.kind == "all":
            return True
        return any(t.matches(*elem) if hasattr(t, "matches") else t == elem[0] for t in self.items)

    def __repr__(self):
        if self.kind in ("none", "all"):
            return self.kind
        return ", ".join(repr(t) for t in self.items)


Targets.NONE = Targets("none")
Targets.ALL = Targets("all")


def parse_targets(spec: Union[str, None], parser) -> Targets:
    """Parse a CLI/env spec: '' or 'none' → None; '*' or 'all' → All;
    otherwise a comma-separated target list."""
    if spec is None:
        return Targets.NONE
    s = spec.strip().lower()
    if s in ("", "none", "false"):
        return Targets.NONE
    if s in ("*", "all", "true"):
        return Targets.ALL
    return Targets.some(parser(p) for p in spec.split(",") if p.strip())


# ------------------------------------------------------------------ capabilities
class Capabilities:
    """A capability allows an element iff allow matches AND deny does not
    (reference capabilities.rs Capabilities::allows_*)."""

    __slots__ = (
        "scripting",
        "guest_access",
        "live_query_notifications",
        "allow_funcs",
        "deny_funcs",
        "allow_net",
        "deny_net",
        "allow_rpc",
        "deny_rpc",
        "allow_http",
        "deny_http",
        "experimental",
    )

    def __init__(self):
        # reference Default: guests denied, functions/rpc/http allowed,
        # outbound network denied
        self.scripting = False
        self.guest_access = False
        self.live_query_notifications = True
        self.allow_funcs = Targets.ALL
        self.deny_funcs = Targets.NONE
        self.allow_net = Targets.NONE
        self.deny_net = Targets.NONE
        self.allow_rpc = Targets.ALL
        self.deny_rpc = Targets.NONE
        self.allow_http = Targets.ALL
        self.deny_http = Targets.NONE
        self.experimental = frozenset()

    @staticmethod
    def default() -> "Capabilities":
        return Capabilities()

    @staticmethod
    def all() -> "Capabilities":
        c = Capabilities()
        c.scripting = True
        c.guest_access = True
        c.allow_net = Targets.ALL
        return c

    @staticmethod
    def none() -> "Capabilities":
        c = Capabilities()
        c.live_query_notifications = False
        c.allow_funcs = Targets.NONE
        c.allow_rpc = Targets.NONE
        c.allow_http = Targets.NONE
        return c

    # ------------------------------------------------------------ builders
    def with_scripting(self, v: bool) -> "Capabilities":
        self.scripting = v
        return self

    def with_guest_access(self, v: bool) -> "Capabilities":
        self.guest_access = v
        return self

    def with_live_query_notifications(self, v: bool) -> "Capabilities":
        self.live_query_notifications = v
        return self

    def with_functions(self, t: Targets) -> "Capabilities":
        self.allow_funcs = t
        return self

    def without_functions(self, t: Targets) -> "Capabilities":
        self.deny_funcs = t
        return self

    def with_network_targets(self, t: Targets) -> "Capabilities":
        self.allow_net = t
        return self

    def without_network_targets(self, t: Targets) -> "Capabilities":
        self.deny_net = t
        return self

    def with_rpc_methods(self, t: Targets) -> "Capabilities":
        self.allow_rpc = t
        return self

    def without_rpc_methods(self, t: Targets) -> "Capabilities":
        self.deny_rpc = t
        return self

    def with_http_routes(self, t: Targets) -> "Capabilities":
        self.allow_http = t
        return self

    def without_http_routes(self, t: Targets) -> "Capabilities":
        self.deny_http = t
        return self

    # ------------------------------------------------------------ checks
    def allows_scripting(self) -> bool:
        return self.scripting

    def allows_guest_access(self) -> bool:
        return self.guest_access

    def allows_live_query_notifications(self) -> bool:
        return self.live_query_notifications

    def allows_function_name(self, name: str) -> bool:
        return self.allow_funcs.matches(name) and not self.deny_funcs.matches(name)

    def allows_network_target(self, host: str, port: Optional[int] = None) -> bool:
        return self.allow_net.matches(host, port) and not self.deny_net.matches(host, port)

    def allows_rpc_method(self, method: str) -> bool:
        m = method.lower()
        return self.allow_rpc.matches(m) and not self.deny_rpc.matches(m)

    def allows_http_route(self, route: str) -> bool:
        r = route.lower()
        return self.allow_http.matches(r) and not self.deny_http.matches(r)

    def __repr__(self):
        return (
            f"scripting={self.scripting}, guest_access={self.guest_access}, "
            f"live_query_notifications={self.live_query_notifications}, "
            f"allow_funcs={self.allow_funcs!r}, deny_funcs={self.deny_funcs!r}, "
            f"allow_net={self.allow_net!r}, deny_net={self.deny_net!r}, "
            f"allow_rpc={self.allow_rpc!r}, deny_rpc={self.deny_rpc!r}, "
            f"allow_http={self.allow_http!r}, deny_http={self.deny_http!r}"
        )


# ------------------------------------------------------------------ env/CLI
def from_env_and_args(args=None) -> Capabilities:
    """Build server capabilities from CLI args (cli.py start) and/or
    SURREAL_CAPS_* environment variables (reference: the --allow-*/--deny-*
    flags on `surreal start`)."""
    from surrealdb_tpu import cnf

    caps = Capabilities.default()
    falsy = ("", "0", "false", "no", "off", "none")

    def flag(cli_name: str, env: str) -> Optional[str]:
        v = getattr(args, cli_name, None) if args is not None else None
        if v is None:
            v = cnf.env_str(env)
        if v is True:
            return "all"
        if v is False:
            return "none"
        return v

    def truthy(v: Optional[str]) -> bool:
        return v is not None and v.strip().lower() not in falsy

    if truthy(flag("allow_all", "SURREAL_CAPS_ALLOW_ALL")):
        caps = Capabilities.all()
    if truthy(flag("deny_all", "SURREAL_CAPS_DENY_ALL")):
        caps = Capabilities.none()

    v = flag("allow_scripting", "SURREAL_CAPS_ALLOW_SCRIPT")
    if v is not None:
        caps.with_scripting(truthy(v))
    v = flag("allow_guests", "SURREAL_CAPS_ALLOW_GUESTS")
    if v is not None:
        caps.with_guest_access(truthy(v))
    v = flag("allow_funcs", "SURREAL_CAPS_ALLOW_FUNC")
    if v is not None:
        caps.with_functions(parse_targets(v, FuncTarget.parse))
    v = flag("deny_funcs", "SURREAL_CAPS_DENY_FUNC")
    if v is not None:
        caps.without_functions(parse_targets(v, FuncTarget.parse))
    v = flag("allow_net", "SURREAL_CAPS_ALLOW_NET")
    if v is not None:
        caps.with_network_targets(parse_targets(v, NetTarget.parse))
    v = flag("deny_net", "SURREAL_CAPS_DENY_NET")
    if v is not None:
        caps.without_network_targets(parse_targets(v, NetTarget.parse))
    v = flag("allow_rpc", "SURREAL_CAPS_ALLOW_RPC")
    if v is not None:
        caps.with_rpc_methods(
            parse_targets(v, lambda s: _Member(_check_member("rpc", s, RPC_METHODS)))
        )
    v = flag("deny_rpc", "SURREAL_CAPS_DENY_RPC")
    if v is not None:
        caps.without_rpc_methods(
            parse_targets(v, lambda s: _Member(_check_member("rpc", s, RPC_METHODS)))
        )
    v = flag("allow_http", "SURREAL_CAPS_ALLOW_HTTP")
    if v is not None:
        caps.with_http_routes(
            parse_targets(v, lambda s: _Member(_check_member("http", s, HTTP_ROUTES)))
        )
    v = flag("deny_http", "SURREAL_CAPS_DENY_HTTP")
    if v is not None:
        caps.without_http_routes(
            parse_targets(v, lambda s: _Member(_check_member("http", s, HTTP_ROUTES)))
        )
    return caps


def check_net_target(caps: Capabilities, url: str) -> None:
    """Chokepoint for outbound network access (http:: functions): parse the
    URL's host/port and raise unless the capability allows it (reference:
    fnc/http.rs net-target check before every request)."""
    from urllib.parse import urlparse

    from surrealdb_tpu.err import NetTargetNotAllowedError

    p = urlparse(url)
    host = p.hostname or ""
    port = p.port or {"http": 80, "https": 443}.get(p.scheme or "", None)
    if not host or not caps.allows_network_target(host, port):
        raise NetTargetNotAllowedError(f"{host}:{port}" if port else host)


class _Member:
    """Exact-string target (RPC methods, HTTP route names)."""

    __slots__ = ("value",)

    def __init__(self, value: str):
        self.value = value

    def matches(self, elem: str) -> bool:
        return elem == self.value

    def __repr__(self):
        return self.value

    def __eq__(self, o):
        return isinstance(o, _Member) and self.value == o.value

    def __hash__(self):
        return hash(self.value)
