"""Session: who is connected and where they point.

Role of the reference's Session (reference: core/src/dbs/session.rs:165):
carries the selected namespace/database, the authentication state, realtime
flag, and the session values exposed to queries ($session, $auth, $access,
$token, $ip, $origin).
"""

from __future__ import annotations

import time
import uuid as _uuid
from typing import Any, Dict, Optional


class Auth:
    """Authentication state (reference: core/src/iam Auth/Actor).

    level: "no" | "record" | "db" | "ns" | "root"
    """

    __slots__ = ("level", "ns", "db", "user", "access", "rid", "roles")

    def __init__(
        self,
        level: str = "no",
        ns: Optional[str] = None,
        db: Optional[str] = None,
        user: Optional[str] = None,
        access: Optional[str] = None,
        rid: Any = None,
        roles: Optional[list] = None,
    ):
        self.level = level
        self.ns = ns
        self.db = db
        self.user = user
        self.access = access
        self.rid = rid  # record id for record-level access
        self.roles = roles or []

    def is_anon(self) -> bool:
        return self.level == "no"

    def is_root(self) -> bool:
        return self.level == "root"

    def is_owner(self) -> bool:
        return self.level == "root" or "Owner" in self.roles

    def has_db_access(self, ns: str, db: str) -> bool:
        if self.level == "root":
            return True
        if self.level == "ns":
            return self.ns == ns
        if self.level in ("db", "record"):
            return self.ns == ns and self.db == db
        return False


class Session:
    __slots__ = ("id", "ns", "db", "auth", "rt", "ip", "origin", "token", "expires")

    def __init__(
        self,
        ns: Optional[str] = None,
        db: Optional[str] = None,
        auth: Optional[Auth] = None,
        rt: bool = False,
    ):
        self.id = str(_uuid.uuid4())
        self.ns = ns
        self.db = db
        self.auth = auth or Auth()
        self.rt = rt  # realtime (live query) capable connection
        self.ip: Optional[str] = None
        self.origin: Optional[str] = None
        self.token: Optional[Dict[str, Any]] = None
        self.expires: Optional[float] = None

    # ------------------------------------------------------------ factories
    @staticmethod
    def owner(ns: Optional[str] = "test", db: Optional[str] = "test") -> "Session":
        """A fully-privileged session (used by embedded/local engines)."""
        return Session(ns, db, Auth("root", roles=["Owner"]), rt=True)

    @staticmethod
    def editor(ns: Optional[str] = "test", db: Optional[str] = "test") -> "Session":
        return Session(ns, db, Auth("root", roles=["Editor"]), rt=True)

    @staticmethod
    def viewer(ns: Optional[str] = "test", db: Optional[str] = "test") -> "Session":
        return Session(ns, db, Auth("root", roles=["Viewer"]), rt=True)

    @staticmethod
    def anonymous(ns: Optional[str] = None, db: Optional[str] = None) -> "Session":
        return Session(ns, db, Auth("no"))

    @staticmethod
    def for_record(ns: str, db: str, access: str, rid) -> "Session":
        return Session(ns, db, Auth("record", ns=ns, db=db, access=access, rid=rid), rt=True)

    # ------------------------------------------------------------ values
    def expired(self) -> bool:
        return self.expires is not None and time.time() > self.expires

    def session_value(self) -> Dict[str, Any]:
        """The $session object."""
        return {
            "id": self.id,
            "ns": self.ns,
            "db": self.db,
            "ip": self.ip,
            "or": self.origin,
            "ac": self.auth.access,
            "rd": self.auth.rid,
            "exp": self.expires,
        }

    def auth_value(self) -> Any:
        """The $auth value: the record id for record access, else NONE."""
        from surrealdb_tpu.sql.value import NONE

        if self.auth.level == "record":
            return self.auth.rid
        return NONE
