"""INFO FOR ... statements.

Role of the reference's InfoStatement::compute (reference:
core/src/sql/statements/info.rs): snapshot the catalog at each level into an
object of `name -> definition-text` maps (or structured objects with
STRUCTURE).
"""

from __future__ import annotations

from typing import Any, Dict

from surrealdb_tpu.err import IxNotFoundError, SurrealError


def info_compute(ctx, stm) -> Any:
    from surrealdb_tpu.iam.check import check_info

    check_info(ctx, stm.level)
    level = stm.level
    txn = ctx.txn()
    structure = stm.structure

    def fmt(items, render):
        out: Dict[str, Any] = {}
        for d in items:
            out[d["name"]] = d if structure else render(d)
        return out

    if level == "root":
        return {
            "namespaces": fmt(txn.all_ns(), _r_ns),
            "users": fmt(txn.all_root_users(), _r_user),
            "accesses": fmt(txn.all_accesses(()), _r_access),
            "nodes": {},
            "system": _system_info(ctx.ds()),
        }
    if level == "ns":
        ns = ctx.session.ns
        return {
            "databases": fmt(txn.all_db(ns), _r_db),
            "users": fmt(txn.all_ns_users(ns), _r_user),
            "accesses": fmt(txn.all_accesses((ns,)), _r_access),
        }
    if level == "db":
        ns, db = ctx.ns_db()
        return {
            "tables": fmt(txn.all_tb(ns, db), _r_tb),
            "users": fmt(txn.all_db_users(ns, db), _r_user),
            "accesses": fmt(txn.all_accesses((ns, db)), _r_access),
            "functions": fmt(txn.all_fc(ns, db), _r_fc),
            "params": fmt(txn.all_pa(ns, db), _r_pa),
            "analyzers": fmt(txn.all_az(ns, db), _r_az),
            "models": fmt(txn.all_ml(ns, db), _r_ml),
            "configs": {},
        }
    if level == "table":
        ns, db = ctx.ns_db()
        tb = stm.target
        txn.expect_tb(ns, db, tb)
        return {
            "fields": fmt(txn.all_tb_fields(ns, db, tb), _r_fd),
            "indexes": fmt(txn.all_tb_indexes(ns, db, tb), _r_ix),
            "events": fmt(txn.all_tb_events(ns, db, tb), _r_ev),
            "tables": fmt(txn.all_tb_views(ns, db, tb), lambda d: d["name"]),
            "lives": {},
        }
    if level == "index":
        ns, db = ctx.ns_db()
        name, _, tb = (stm.target or "").partition(":")
        ix = txn.get_tb_index(ns, db, tb, name)
        if ix is None:
            raise IxNotFoundError(name)
        building: Dict[str, Any] = {"status": ix.get("status", "ready")}
        live = ctx.ds().index_builder.status(ns, db, tb, name)
        if live is not None:
            building.update(live)
        out: Dict[str, Any] = {"building": building}
        # ANN state: a trained/stale/absent IVF over the vector mirror
        if ix.get("index", {}).get("type") in ("hnsw", "mtree"):
            mirror = ctx.ds().index_stores.get(ns, db, tb, name)
            if mirror is not None and hasattr(mirror, "ivf_status"):
                out["ann"] = mirror.ivf_status()
        return out
    if level == "user":
        user = stm.target
        d = txn.get_root_user(user)
        if d is None:
            raise SurrealError(f"The root user '{user}' does not exist")
        return d if structure else _r_user(d)
    raise SurrealError(f"INFO FOR {level} is not supported")


def _system_info(ds=None) -> Dict[str, Any]:
    """Embedded-user access to the slow-query ring, error ring, trace
    store, and the full flight-recorder bundle (these were HTTP-only —
    GET /slow, /traces, /debug/bundle — which left SDK/embedded
    deployments blind). INFO FOR ROOT is already gated to root-level
    users, the same bar as the HTTP endpoints. Traces are the bounded
    store's summaries; fetch one in full by id via `traces` ->
    tracing.get_trace (or GET /trace/:id on a server)."""
    from surrealdb_tpu import accounting, advisor, stats, telemetry, tracing
    from surrealdb_tpu.bundle import debug_bundle

    return {
        "slow_queries": telemetry.slow_queries(),
        "errors": telemetry.recent_errors(),
        "traces": tracing.list_traces(limit=50),
        # workload statistics plane: the top statement shapes by
        # cumulative time, with plan-mix vectors + flip counts (stats.py)
        "statements": stats.statements(limit=20),
        # tenant cost-attribution plane: the top (ns, db) pairs by
        # cumulative execution time (accounting.py)
        "tenants": accounting.top(limit=20),
        # advisor plane: live evidence-chained tuning proposals + sweep
        # health (advisor.py; observe-only — nothing is ever applied)
        "advisor": advisor.snapshot(limit=20),
        # the flight-recorder bundle for embedded users. full_traces=0: the
        # rings/summaries above already cover them, and re-materializing the
        # newest full span trees would double this (routine, root-gated)
        # statement's serialization cost; fetch a tree by id via `traces`.
        "bundle": debug_bundle(ds, full_traces=0),
    }


# ------------------------------------------------------------------ renderers
def _r_ns(d) -> str:
    return f"DEFINE NAMESPACE {d['name']}"


def _r_db(d) -> str:
    out = f"DEFINE DATABASE {d['name']}"
    if d.get("changefeed"):
        out += f" CHANGEFEED {d['changefeed']['expiry'] // 10**9}s"
    return out


def _r_tb(d) -> str:
    out = f"DEFINE TABLE {d['name']}"
    out += " TYPE " + d.get("kind", "ANY")
    if d.get("kind") == "RELATION":
        if d.get("relation_in"):
            out += " IN " + "|".join(d["relation_in"])
        if d.get("relation_out"):
            out += " OUT " + "|".join(d["relation_out"])
    out += " SCHEMAFULL" if d.get("schemafull") else " SCHEMALESS"
    if d.get("drop"):
        out += " DROP"
    if d.get("changefeed"):
        out += f" CHANGEFEED {d['changefeed']['expiry'] // 10**9}s"
    return out


def _r_fd(d) -> str:
    out = f"DEFINE FIELD {d['name']} ON {d['table']}"
    if d.get("flex"):
        out += " FLEXIBLE"
    if d.get("kind") is not None:
        out += f" TYPE {d['kind']!r}"
    if d.get("default") is not None:
        out += f" DEFAULT {d['default']!r}"
    if d.get("value") is not None:
        out += f" VALUE {d['value']!r}"
    if d.get("assert") is not None:
        out += f" ASSERT {d['assert']!r}"
    if d.get("readonly"):
        out += " READONLY"
    return out


def _r_ix(d) -> str:
    out = f"DEFINE INDEX {d['name']} ON {d['table']}"
    if d.get("fields"):
        out += " FIELDS " + ", ".join(repr(f) for f in d["fields"])
    ix = d.get("index", {})
    t = ix.get("type")
    if t == "uniq":
        out += " UNIQUE"
    elif t == "search":
        out += f" SEARCH ANALYZER {ix.get('analyzer')} BM25({ix.get('k1')},{ix.get('b')})"
        if ix.get("highlights"):
            out += " HIGHLIGHTS"
    elif t == "mtree":
        out += f" MTREE DIMENSION {ix.get('dimension')} DIST {ix.get('dist').upper()}"
    elif t == "hnsw":
        out += (
            f" HNSW DIMENSION {ix.get('dimension')} DIST {ix.get('dist').upper()}"
            f" EFC {ix.get('efc')} M {ix.get('m')}"
        )
    return out


def _r_ev(d) -> str:
    whens = f" WHEN {d['when']!r}" if d.get("when") else ""
    thens = ", ".join(repr(t) for t in d.get("then", []))
    return f"DEFINE EVENT {d['name']} ON {d['table']}{whens} THEN {thens}"


def _r_user(d) -> str:
    roles = ", ".join(d.get("roles", []))
    return f"DEFINE USER {d['name']} ON {d.get('base', 'root').upper()} PASSHASH '***' ROLES {roles}"


def _r_access(d) -> str:
    return f"DEFINE ACCESS {d['name']} ON {d.get('base', 'db').upper()} TYPE {(d.get('access_type') or '').upper()}"


def _r_fc(d) -> str:
    ps = ", ".join(f"${p}: {k!r}" for p, k in d.get("params", []))
    return f"DEFINE FUNCTION fn::{d['name']}({ps}) {d.get('body')!r}"


def _r_pa(d) -> str:
    from surrealdb_tpu.sql.value import format_value

    return f"DEFINE PARAM ${d['name']} VALUE {format_value(d.get('value'))}"


def _r_az(d) -> str:
    out = f"DEFINE ANALYZER {d['name']}"
    if d.get("tokenizers"):
        out += " TOKENIZERS " + ",".join(d["tokenizers"])
    if d.get("filters"):
        out += " FILTERS " + ",".join(f["name"] for f in d["filters"])
    return out


def _r_ml(d) -> str:
    return f"DEFINE MODEL ml::{d['name']}<{d.get('version')}>"
