"""Live-query notifications.

Role of the reference's Notification type + channel plumbing (reference:
core/src/dbs/notification.rs, core/src/doc/lives.rs): mutations on tables
with registered LIVE queries emit Notification{id, action, record, result}
into per-subscription queues, delivered only after the writing transaction
commits.
"""

from __future__ import annotations

import queue
from surrealdb_tpu.utils import locks as _locks
from typing import Any, Dict, List, Optional


class Notification:
    __slots__ = ("id", "action", "record", "result")

    def __init__(self, id_: str, action: str, record, result):
        self.id = id_  # live query uuid (hex string)
        self.action = action  # CREATE | UPDATE | DELETE | KILLED
        self.record = record  # Thing
        self.result = result

    def to_value(self) -> dict:
        return {
            "id": self.id,
            "action": self.action,
            "record": self.record,
            "result": self.result,
        }

    def __repr__(self):
        return f"Notification({self.action} {self.record})"


class NotificationHub:
    """Routes notifications to per-live-query subscriber queues."""

    def __init__(self):
        self._subs: Dict[str, "queue.Queue[Notification]"] = {}
        self._lock = _locks.Lock("notification.hub")

    def subscribe(self, live_id: str) -> "queue.Queue[Notification]":
        with self._lock:
            q = self._subs.get(live_id)
            if q is None:
                q = queue.Queue()
                self._subs[live_id] = q
            return q

    def unsubscribe(self, live_id: str) -> None:
        with self._lock:
            self._subs.pop(live_id, None)

    def live_count(self) -> int:
        """Open live-query subscriptions (the node runtime gauge)."""
        with self._lock:
            return len(self._subs)

    def publish(self, n: Notification) -> None:
        with self._lock:
            q = self._subs.get(n.id)
        if q is not None:
            q.put(n)

    def drain(self, live_id: str, timeout: Optional[float] = None) -> List[Notification]:
        """Collect pending notifications for one live query (test helper)."""
        q = self.subscribe(live_id)
        out: List[Notification] = []
        try:
            if timeout:
                out.append(q.get(timeout=timeout))
            while True:
                out.append(q.get_nowait())
        except queue.Empty:
            pass
        return out
