"""DEFINE / REMOVE / ALTER / REBUILD execution.

Role of the reference's define/remove/alter statement computes (reference:
core/src/sql/statements/define/, remove/, alter/): persist catalog
definitions into the keyspace and run side effects (index builds, view
bootstraps).
"""

from __future__ import annotations

import secrets
from typing import Any, Optional

from surrealdb_tpu import key as keys
from surrealdb_tpu.err import IxNotFoundError, SurrealError, TbNotFoundError
from surrealdb_tpu.sql.value import NONE, Thing


class _AlreadyExists(SurrealError):
    def __init__(self, kind: str, name: str):
        super().__init__(f"The {kind} '{name}' already exists")


def _guard(existing, args, kind: str, name: str) -> bool:
    """Handle IF NOT EXISTS / OVERWRITE. Returns True when the define should
    be skipped."""
    if existing is not None:
        if args.get("if_not_exists"):
            return True
        if not args.get("overwrite"):
            raise _AlreadyExists(kind, name)
    return False


def define_compute(ctx, stm) -> Any:
    from surrealdb_tpu.iam.check import check_ddl

    kind = stm.kind
    target_base = stm.args.get("base") if kind in ("user", "access") else None
    check_ddl(ctx, kind, target_base=target_base)
    args = stm.args
    handler = _DEFINES.get(kind)
    if handler is None:
        raise SurrealError(f"DEFINE {kind.upper()} is not supported")
    return handler(ctx, args)


# ------------------------------------------------------------------ handlers
def _def_namespace(ctx, a) -> Any:
    txn = ctx.txn()
    name = a["name"]
    if _guard(txn.get_ns(name), a, "namespace", name):
        return NONE
    txn.put_ns(name, {"name": name, "comment": a.get("comment")})
    return NONE


def _def_database(ctx, a) -> Any:
    txn = ctx.txn()
    ns = ctx.session.ns
    name = a["name"]
    txn.ensure_ns(ns)
    if _guard(txn.get_db(ns, name), a, "database", name):
        return NONE
    txn.put_db(ns, name, {
        "name": name,
        "changefeed": a.get("changefeed"),
        "comment": a.get("comment"),
    })
    return NONE


def _def_table(ctx, a) -> Any:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    name = a["name"]
    txn.ensure_db(ns, db)
    if _guard(txn.get_tb(ns, db, name), a, "table", name):
        return NONE
    d = {
        "name": name,
        "drop": a.get("drop", False),
        "schemafull": a.get("schemafull", False),
        "kind": a.get("kind", "ANY"),
        "relation_in": a.get("relation_in"),
        "relation_out": a.get("relation_out"),
        "enforced": a.get("enforced", False),
        "view": a.get("view"),
        "permissions": a.get("permissions"),
        "changefeed": a.get("changefeed"),
        "comment": a.get("comment"),
    }
    txn.put_tb(ns, db, name, d)
    if d["view"] is not None:
        _bootstrap_view(ctx, name, d["view"])
    return NONE


def _bootstrap_view(ctx, view_name: str, sel) -> None:
    """Register the view link on each source table and materialize the
    initial contents (reference: doc/table.rs foreign tables)."""
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    from surrealdb_tpu.sql.value import Table
    from surrealdb_tpu.sql.path import Idiom, PField

    for w in sel.what:
        src = w.compute(ctx)
        if isinstance(src, Table):
            txn.ensure_tb(ns, db, str(src))
            txn.put_tb_view(ns, db, str(src), view_name, {"name": view_name})
    from surrealdb_tpu.doc.views import materialize_view

    materialize_view(ctx, view_name, sel)


def _def_field(ctx, a) -> Any:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    tb = a["table"]
    name = repr(a["name"]) if not isinstance(a["name"], str) else a["name"]
    txn.ensure_tb(ns, db, tb)
    if _guard(txn.get_tb_field(ns, db, tb, name), a, "field", name):
        return NONE
    txn.put_tb_field(ns, db, tb, name, {
        "name": name,
        "table": tb,
        "flex": a.get("flex", False),
        "kind": a.get("kind"),
        "readonly": a.get("readonly", False),
        "value": a.get("value"),
        "assert": a.get("assert"),
        "default": a.get("default"),
        "default_always": a.get("default_always", False),
        "permissions": a.get("permissions"),
        "comment": a.get("comment"),
    })
    return NONE


def _def_index(ctx, a) -> Any:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    tb = a["table"]
    name = a["name"]
    txn.ensure_tb(ns, db, tb)
    if _guard(txn.get_tb_index(ns, db, tb, name), a, "index", name):
        return NONE
    concurrent = bool(a.get("concurrently"))
    d = {
        "name": name,
        "table": tb,
        "fields": a.get("fields", []),
        "index": a.get("index", {"type": "idx"}),
        "comment": a.get("comment"),
        "status": "building" if concurrent else "ready",
    }
    txn.put_tb_index(ns, db, tb, name, d)
    if concurrent:
        # async initial build (reference kvs/index.rs): kick AFTER this
        # transaction commits so the builder's txns see the definition;
        # the planner refuses the index until its status flips to ready
        ds = ctx.ds()
        sess = ctx.session

        txn.on_commit(lambda: ds.index_builder.build(ns, db, tb, d, sess))
        return NONE
    # inline build over existing records
    from surrealdb_tpu.idx.index import rebuild_index

    rebuild_index(ctx, tb, d)
    return NONE


def _def_event(ctx, a) -> Any:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    tb = a["table"]
    name = a["name"]
    txn.ensure_tb(ns, db, tb)
    if _guard(txn.get_tb_event(ns, db, tb, name), a, "event", name):
        return NONE
    txn.put_tb_event(ns, db, tb, name, {
        "name": name,
        "table": tb,
        "when": a.get("when"),
        "then": a.get("then", []),
        "comment": a.get("comment"),
    })
    return NONE


def _def_analyzer(ctx, a) -> Any:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    name = a["name"]
    txn.ensure_db(ns, db)
    if _guard(txn.get_az(ns, db, name), a, "analyzer", name):
        return NONE
    txn.put_az(ns, db, name, {
        "name": name,
        "tokenizers": a.get("tokenizers", []),
        "filters": a.get("filters", []),
        "function": a.get("function"),
        "comment": a.get("comment"),
    })
    return NONE


def _def_function(ctx, a) -> Any:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    name = a["name"]
    txn.ensure_db(ns, db)
    if _guard(txn.get_fc(ns, db, name), a, "function", name):
        return NONE
    txn.put_fc(ns, db, name, {
        "name": name,
        "params": a.get("params", []),
        "body": a.get("body"),
        "returns": a.get("returns"),
        "permissions": a.get("permissions"),
        "comment": a.get("comment"),
    })
    return NONE


def _def_param(ctx, a) -> Any:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    name = a["name"]
    txn.ensure_db(ns, db)
    if _guard(txn.get_pa(ns, db, name), a, "param", name):
        return NONE
    value = a.get("value")
    if value is not None and hasattr(value, "compute"):
        value = value.compute(ctx)
    txn.put_pa(ns, db, name, {
        "name": name,
        "value": value,
        "permissions": a.get("permissions"),
        "comment": a.get("comment"),
    })
    return NONE


def _def_user(ctx, a) -> Any:
    txn = ctx.txn()
    name = a["name"]
    base = a.get("base", "root")

    # resolve the existence guard BEFORE paying the KDF cost
    if base == "root":
        existing = txn.get_root_user(name)
    elif base == "ns":
        txn.ensure_ns(ctx.session.ns)
        existing = txn.get_ns_user(ctx.session.ns, name)
    else:
        ns, db = ctx.ns_db()
        txn.ensure_db(ns, db)
        existing = txn.get_db_user(ns, db, name)
    if _guard(existing, a, "user", name):
        return NONE

    from surrealdb_tpu.iam.password import hash_password

    password = a.get("password")
    passhash = a.get("passhash") or (hash_password(password) if password else None)
    d = {
        "name": name,
        "base": base,
        "hash": passhash,
        "roles": a.get("roles", ["Viewer"]),
        "token_duration": a.get("token_duration"),
        "session_duration": a.get("session_duration"),
        "comment": a.get("comment"),
    }
    if base == "root":
        txn.put_root_user(name, d)
    elif base == "ns":
        txn.put_ns_user(ctx.session.ns, name, d)
    else:
        ns, db = ctx.ns_db()
        txn.put_db_user(ns, db, name, d)
    return NONE


def _def_access(ctx, a) -> Any:
    txn = ctx.txn()
    name = a["name"]
    base = a.get("base", "db")
    level = _access_level(ctx, base)
    if _guard(txn.get_access(level, name), a, "access", name):
        return NONE
    txn.put_access(level, name, {
        "name": name,
        "base": base,
        "access_type": a.get("access_type"),
        "signup": a.get("signup"),
        "signin": a.get("signin"),
        "authenticate": a.get("authenticate"),
        "jwt_alg": a.get("jwt_alg", "HS512"),
        # no WITH KEY → random secret, so issued tokens verify on the way back
        # in (reference: define/access.rs random_key())
        "jwt_key": a.get("jwt_key") or secrets.token_urlsafe(32),
        "jwt_url": a.get("jwt_url"),
        "jwt_issuer_key": a.get("jwt_issuer_key"),
        "token_duration": a.get("token_duration"),
        "session_duration": a.get("session_duration"),
        # unspecified -> 30d default (reference: access/DEFAULT_GRANT_DURATION);
        # explicit `DURATION FOR GRANT NONE` stores None (never expires)
        "grant_duration": a.get("grant_duration", 30 * 24 * 3600 * 1_000_000_000),
        "bearer_subject": a.get("bearer_subject"),
        "comment": a.get("comment"),
    })
    return NONE


def _access_level(ctx, base: str) -> tuple:
    if base == "root":
        return ()
    if base == "ns":
        return (ctx.session.ns,)
    return ctx.ns_db()


def _def_model(ctx, a) -> Any:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    name, version = a["name"], a.get("version", "")
    txn.ensure_db(ns, db)
    existing = txn.get_ml(ns, db, name, version)
    if _guard(existing, a, "model", name):
        return NONE
    d = {
        "name": name,
        "version": version,
        "permissions": a.get("permissions"),
        "comment": a.get("comment"),
    }
    if existing:  # OVERWRITE re-defines metadata but keeps stored weights
        for k in ("blob", "in_dim", "out_dim"):
            if k in existing:
                d[k] = existing[k]
    txn.put_ml(ns, db, name, version, d)
    return NONE


def _def_config(ctx, a) -> Any:
    return NONE


_DEFINES = {
    "namespace": _def_namespace,
    "database": _def_database,
    "table": _def_table,
    "field": _def_field,
    "index": _def_index,
    "event": _def_event,
    "analyzer": _def_analyzer,
    "function": _def_function,
    "param": _def_param,
    "user": _def_user,
    "access": _def_access,
    "model": _def_model,
    "config": _def_config,
}


# ------------------------------------------------------------------ REMOVE
def remove_compute(ctx, stm) -> Any:
    from surrealdb_tpu.iam.check import check_ddl

    kind, name = stm.kind, stm.name
    target_base = (stm.level or "root") if kind in ("user", "access") else None
    check_ddl(ctx, kind, target_base=target_base)
    txn = ctx.txn()

    def missing(what: str):
        if stm.if_exists:
            return NONE
        raise SurrealError(f"The {what} '{name}' does not exist")

    if kind == "namespace":
        if txn.get_ns(name) is None:
            return missing("namespace")
        from surrealdb_tpu.key.encode import prefix_end

        txn.del_ns(name)
        pre = keys._ns(name)
        txn.delr(pre, prefix_end(pre))
        txn.touch_scope((name,))
        ds = ctx.ds()
        from surrealdb_tpu.ml.exec import invalidate_ns as _ml_invalidate_ns

        txn.on_commit(lambda: ds.graph_mirrors.drop_ns(name))
        txn.on_commit(lambda: ds.index_stores.remove_ns(name))
        txn.on_commit(lambda: ds.column_mirrors.drop_ns(name))
        txn.on_commit(lambda: _ml_invalidate_ns(ds, name))
        return NONE
    if kind == "database":
        ns = ctx.session.ns
        if txn.get_db(ns, name) is None:
            return missing("database")
        from surrealdb_tpu.key.encode import prefix_end

        txn.del_db(ns, name)
        pre = keys._db(ns, name)
        txn.delr(pre, prefix_end(pre))
        txn.touch_scope((ns, name))
        ds = ctx.ds()
        from surrealdb_tpu.ml.exec import invalidate_db as _ml_invalidate_db

        txn.on_commit(lambda: ds.graph_mirrors.drop_db(ns, name))
        txn.on_commit(lambda: ds.index_stores.remove_db(ns, name))
        txn.on_commit(lambda: ds.column_mirrors.drop_db(ns, name))
        txn.on_commit(lambda: _ml_invalidate_db(ds, ns, name))
        return NONE
    if kind == "table":
        ns, db = ctx.ns_db()
        if txn.get_tb(ns, db, name) is None:
            return missing("table")
        from surrealdb_tpu.key.encode import prefix_end

        txn.del_tb(ns, db, name)
        pre = keys.table_all_prefix(ns, db, name)
        txn.delr(pre, prefix_end(pre))
        txn.touch_scope((ns, db, name))
        ds = ctx.ds()
        txn.on_commit(lambda: ds.index_stores.remove_table(ns, db, name))
        txn.on_commit(lambda: ds.graph_mirrors.drop_table(ns, db, name))
        txn.on_commit(lambda: ds.column_mirrors.drop_table(ns, db, name))
        return NONE
    if kind == "field":
        ns, db = ctx.ns_db()
        if txn.get_tb_field(ns, db, stm.table, name) is None:
            return missing("field")
        txn.del_tb_field(ns, db, stm.table, name)
        return NONE
    if kind == "index":
        ns, db = ctx.ns_db()
        if txn.get_tb_index(ns, db, stm.table, name) is None:
            return missing("index")
        from surrealdb_tpu.key.encode import prefix_end

        txn.del_tb_index(ns, db, stm.table, name)
        pre = keys.index_prefix(ns, db, stm.table, name)
        txn.delr(pre, prefix_end(pre))
        ds = ctx.ds()
        txn.on_commit(lambda: ds.index_stores.remove(ns, db, stm.table, name))
        return NONE
    if kind == "event":
        ns, db = ctx.ns_db()
        if txn.get_tb_event(ns, db, stm.table, name) is None:
            return missing("event")
        txn.del_tb_event(ns, db, stm.table, name)
        return NONE
    if kind == "analyzer":
        ns, db = ctx.ns_db()
        if txn.get_az(ns, db, name) is None:
            return missing("analyzer")
        txn.del_az(ns, db, name)
        return NONE
    if kind == "function":
        ns, db = ctx.ns_db()
        fname = name
        if txn.get_fc(ns, db, fname) is None:
            return missing("function")
        txn.del_fc(ns, db, fname)
        return NONE
    if kind == "param":
        ns, db = ctx.ns_db()
        if txn.get_pa(ns, db, name) is None:
            return missing("param")
        txn.del_pa(ns, db, name)
        return NONE
    if kind == "user":
        base = stm.level or "root"
        if base == "root":
            if txn.get_root_user(name) is None:
                return missing("user")
            txn.del_root_user(name)
        elif base == "ns":
            ns = ctx.session.ns
            if txn.get_ns_user(ns, name) is None:
                return missing("user")
            txn.del_ns_user(ns, name)
        else:
            ns, db = ctx.ns_db()
            if txn.get_db_user(ns, db, name) is None:
                return missing("user")
            txn.del_db_user(ns, db, name)
        return NONE
    if kind == "access":
        level = _access_level(ctx, stm.level or "db")
        if txn.get_access(level, name) is None:
            return missing("access")
        txn.del_access(level, name)
        return NONE
    if kind == "model":
        ns, db = ctx.ns_db()
        version = getattr(stm, "table", None) or ""
        entry = txn.get_ml(ns, db, name, version)
        if entry is None:
            return missing("model")
        txn.del_ml(ns, db, name, version)
        # GC the content-addressed weights blob unless another model version
        # still references the same digest (advisor r2: orphaned blobs)
        digest = entry.get("blob")
        if digest and not any(m.get("blob") == digest for m in txn.all_ml(ns, db)):
            from surrealdb_tpu.obs import del_blob

            del_blob(txn, ns, db, digest)
        ds = ctx.ds()
        from surrealdb_tpu.ml.exec import invalidate as _ml_invalidate

        txn.on_commit(lambda: _ml_invalidate(ds, ns, db, name, version))
        return NONE
    raise SurrealError(f"REMOVE {kind.upper()} is not supported")


# ------------------------------------------------------------------ ALTER / REBUILD
def alter_compute(ctx, stm) -> Any:
    from surrealdb_tpu.iam.check import check_ddl

    check_ddl(ctx, stm.kind)
    if stm.kind != "table":
        raise SurrealError(f"ALTER {stm.kind.upper()} is not supported")
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    d = txn.get_tb(ns, db, stm.name)
    if d is None:
        if stm.if_exists:
            return NONE
        raise TbNotFoundError(stm.name)
    for k, v in stm.args.items():
        if v is not None and k in d:
            d[k] = v
    txn.put_tb(ns, db, stm.name, d)
    return NONE


def rebuild_compute(ctx, stm) -> Any:
    from surrealdb_tpu.iam.check import check_ddl

    check_ddl(ctx, "index")
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    ix = txn.get_tb_index(ns, db, stm.table, stm.name)
    if ix is None:
        if stm.if_exists:
            return NONE
        raise IxNotFoundError(stm.name)
    from surrealdb_tpu.idx.index import rebuild_index

    rebuild_index(ctx, stm.table, ix)
    return NONE
