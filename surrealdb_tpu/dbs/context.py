"""Execution context.

Role of the reference's Context chain + CursorDoc (reference:
core/src/ctx/context.rs:43-430, core/src/doc/document.rs): a chain of scopes
carrying parameters, the current document binding, depth tracking, options,
deadline, and handles back to the executor (transaction) and the per-query
index executor.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

from surrealdb_tpu import cnf
from surrealdb_tpu.err import (
    ComputationDepthError,
    DbNotFoundError,
    NsNotFoundError,
    QueryTimeoutError,
)
from surrealdb_tpu.sql.value import NONE, Thing, copy_value


class CursorDoc:
    """The record a statement is currently processing.

    rid:      record id (Thing) or None for plain values
    current:  the working value (mutated by the doc pipeline)
    initial:  deep copy of the value before this statement touched it
    ir:       index result metadata (doc_id, distance, score) when the record
              came from an index iterator (reference IteratorRecord)
    """

    __slots__ = ("rid", "current", "initial", "ir")

    def __init__(self, rid: Optional[Thing], current: Any, initial: Any = None, ir=None):
        self.rid = rid
        self.current = current
        self.initial = initial if initial is not None else copy_value(current)
        self.ir = ir


class Context:
    __slots__ = (
        "executor",
        "session",
        "parent",
        "params",
        "doc",
        "depth",
        "options",
        "deadline",
        "qe",
        "stm",
    )

    def __init__(self, executor, session, parent: Optional["Context"] = None):
        self.executor = executor
        self.session = session
        self.parent = parent
        self.params: Dict[str, Any] = {}
        self.doc: Optional[CursorDoc] = None
        self.depth = 0
        self.options: Dict[str, Any] = {}
        self.deadline: Optional[float] = None
        self.qe = None  # per-table QueryExecutor (set by the iterator)
        self.stm = None  # current statement view
        if parent is not None:
            self.doc = parent.doc
            self.depth = parent.depth
            self.deadline = parent.deadline
            self.qe = parent.qe
            self.stm = parent.stm

    # ------------------------------------------------------------ scoping
    def _child(self) -> "Context":
        return Context(self.executor, self.session, parent=self)

    @contextmanager
    def child_scope(self):
        """New parameter scope (block / closure body)."""
        yield self._child()

    @contextmanager
    def descend(self):
        """Depth-limited descent into a subquery/function/future."""
        c = self._child()
        c.depth = self.depth + 1
        if c.depth > cnf.MAX_COMPUTATION_DEPTH:
            raise ComputationDepthError()
        yield c

    @contextmanager
    def with_doc(self, doc: Optional[CursorDoc]):
        c = self._child()
        if self.doc is not None:
            c.params["parent"] = self.doc.current
        c.doc = doc
        yield c

    @contextmanager
    def with_doc_value(self, value, rid: Optional[Thing] = None, ir=None):
        c = self._child()
        if self.doc is not None:
            c.params["parent"] = self.doc.current
        c.doc = CursorDoc(rid, value, initial=value, ir=ir)
        yield c

    # ------------------------------------------------------------ params
    def set_param(self, name: str, value: Any) -> None:
        self.params[name] = value

    def get_param(self, name: str) -> Any:
        # document bindings take precedence
        if self.doc is not None:
            if name == "this":
                return self.doc.current
        node: Optional[Context] = self
        while node is not None:
            if name in node.params:
                return node.params[name]
            node = node.parent
        # session-provided values
        if name == "session":
            return self.session.session_value()
        if name == "auth":
            return self.session.auth_value()
        if name == "access":
            return self.session.auth.access or NONE
        if name == "token":
            return self.session.token or NONE
        # database-defined params (DEFINE PARAM)
        v = self._db_param(name)
        if v is not None:
            return v
        return NONE

    def _db_param(self, name: str):
        try:
            ns, db = self.ns_db()
        except (NsNotFoundError, DbNotFoundError):
            return None
        txn = self.txn()
        if txn is None:
            return None
        pa = txn.get_pa(ns, db, name)
        if pa is None:
            return None
        val = pa.get("value")
        from surrealdb_tpu.sql.ast import Expr

        if isinstance(val, Expr):
            return val.compute(self)
        return val

    # ------------------------------------------------------------ options
    def set_option(self, name: str, value: Any) -> None:
        node = self
        while node.parent is not None:
            node = node.parent
        node.options[name.upper()] = value

    def get_option(self, name: str, default: Any = None) -> Any:
        node: Optional[Context] = self
        while node is not None:
            if name.upper() in node.options:
                return node.options[name.upper()]
            node = node.parent
        return default

    @property
    def opt_futures(self) -> bool:
        return bool(self.get_option("FUTURES", True))

    @property
    def opt_import(self) -> bool:
        return bool(self.get_option("IMPORT", False))

    # ------------------------------------------------------------ handles
    def txn(self):
        return self.executor.current_txn()

    def ds(self):
        return self.executor.ds

    def capabilities(self):
        """Datastore-wide allow/deny policy (dbs/capabilities.py)."""
        return self.executor.ds.capabilities

    def ns_db(self):
        ns, db = self.session.ns, self.session.db
        if not ns:
            raise NsNotFoundError("(unset)")
        if not db:
            raise DbNotFoundError("(unset)")
        return ns, db

    def doc_value(self):
        return self.doc.current if self.doc is not None else NONE

    def query_executor(self):
        return self.qe

    # ------------------------------------------------------------ deadline
    @contextmanager
    def with_deadline(self, seconds: Optional[float]):
        c = self._child()
        if seconds is not None:
            dl = time.monotonic() + seconds
            c.deadline = dl if c.deadline is None else min(c.deadline, dl)
        yield c

    def check_deadline(self) -> None:
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise QueryTimeoutError()

    # ------------------------------------------------------------ notifications
    def notify(self, notification) -> None:
        """Buffer a live-query notification; delivered at txn commit
        (reference: executor.rs flush on commit)."""
        self.executor.buffer_notification(notification)
