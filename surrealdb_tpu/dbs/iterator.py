"""Statement iteration: source collection, record processing, postprocessing.

Role of the reference's Iterator + Iterable + Processor trio (reference:
core/src/dbs/iterator.rs:44-808, processor.rs:23-754): a statement's FROM
targets are classified into Iterables (value, thing, range, table, edges,
mergeable, relatable, index plan); each expands into processed records; the
per-verb document pipeline runs per record; SELECT output then flows through
SPLIT → GROUP → ORDER → START/LIMIT → FETCH postprocessing
(iterator.rs:306-394).

The batch boundary: table/index scans fetch in NORMAL_FETCH_SIZE batches, and
index-backed kNN/BM25 sources arrive as whole scored device batches — this is
the seam where the reference's PARALLEL thread pipeline becomes a TPU batch
dispatch (SURVEY §2.5).
"""

from __future__ import annotations

import random
from typing import Any, Iterable as PyIterable, List, Optional, Tuple

from surrealdb_tpu import cnf
from surrealdb_tpu import key as keys
from surrealdb_tpu.err import (
    IgnoreError,
    InvalidStatementTargetError,
    SurrealError,
    TypeError_,
)
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.sql.ast import (
    Expr,
    FunctionCall,
    ThingRange,
)
from surrealdb_tpu.sql.path import Idiom, PField, PGraph, PStart, get_path, set_path
from surrealdb_tpu.sql.value import (
    NONE,
    Range,
    Table,
    Thing,
    copy_value,
    format_value,
    is_none,
    is_nullish,
    sort_key,
    truthy,
    value_cmp,
    value_eq,
)
from surrealdb_tpu.utils.ser import unpack


# ------------------------------------------------------------------ iterables
class IValue:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v


class IThing:
    __slots__ = ("t",)

    def __init__(self, t: Thing):
        self.t = t


class IDefer:
    """A record id for CREATE — existence checked at write time."""

    __slots__ = ("t",)

    def __init__(self, t: Thing):
        self.t = t


class IRange:
    __slots__ = ("tb", "rng")

    def __init__(self, tb: str, rng: Range):
        self.tb = tb
        self.rng = rng


class ITable:
    __slots__ = ("tb",)

    def __init__(self, tb: str):
        self.tb = tb


class IMergeable:
    __slots__ = ("t", "row")

    def __init__(self, t: Thing, row: dict):
        self.t = t
        self.row = row


class IRelatable:
    __slots__ = ("f", "e", "w", "row")

    def __init__(self, f: Thing, e: Thing, w: Thing, row: Optional[dict] = None):
        self.f = f
        self.e = e
        self.w = w
        self.row = row  # extra fields from INSERT RELATION


class IIndex:
    """Planner-selected index scan (reference Iterable::Index)."""

    __slots__ = ("tb", "plan")

    def __init__(self, tb: str, plan):
        self.tb = tb
        self.plan = plan


# ------------------------------------------------------------------ source classification
def target_value(ctx, e: Expr):
    """Evaluate a statement-target expression. A bare identifier in target
    position always denotes a table, even when a document is bound (the
    reference parses targets as Table values, not idioms)."""
    if isinstance(e, Idiom):
        name = e.simple_name()
        if name is not None:
            return Table(name)
    return e.compute(ctx)


def classify_sources(ctx, what_exprs: List[Expr], verb: str) -> List[Any]:
    """Evaluate FROM/target expressions into Iterables
    (reference: statements/select.rs what-loop + iterator.rs ingest)."""
    out: List[Any] = []
    for e in what_exprs:
        v = target_value(ctx, e)
        _classify_value(ctx, v, verb, out)
    return out


def _classify_value(ctx, v, verb: str, out: List[Any]) -> None:
    if isinstance(v, Table):
        if verb == "create":
            out.append(IDefer(Thing(str(v))))
        else:
            out.append(ITable(str(v)))
    elif isinstance(v, Thing):
        if isinstance(v.id, Range):
            out.append(IRange(v.tb, v.id))
        elif verb == "create":
            out.append(IDefer(v))
        else:
            out.append(IThing(v))
    elif isinstance(v, ThingRange):
        out.append(IRange(v.tb, v.rng))
    elif isinstance(v, (list, tuple)):
        for item in v:
            _classify_value(ctx, item, verb, out)
    elif isinstance(v, str) and verb != "select":
        # string record id like "person:1" used as a write target
        try:
            t = Thing.parse(v)
            _classify_value(ctx, t, verb, out)
        except SurrealError:
            raise InvalidStatementTargetError(format_value(v))
    else:
        if verb == "select":
            out.append(IValue(v))
        else:
            raise InvalidStatementTargetError(format_value(v))


# ------------------------------------------------------------------ record streams
def scan_table(ctx, tb: str) -> PyIterable[Tuple[Thing, dict]]:
    from surrealdb_tpu import accounting

    ns, db = ctx.ns_db()
    txn = ctx.txn()
    pre = keys.thing_prefix(ns, db, tb)
    # deadline checks amortized to every Nth row: a monotonic clock read
    # per row is measurable GIL-held overhead on a million-row scan
    interval = max(cnf.SCAN_DEADLINE_INTERVAL, 1)
    n = 0
    for chunk in txn.batch(pre, prefix_end(pre), cnf.NORMAL_FETCH_SIZE):
        # rows-scanned tally per CHUNK, not per row: the statement-local
        # scratch the executor flushes into its one accounting.charge()
        accounting.tally(rows_scanned=len(chunk))
        for k, raw in chunk:
            if n % interval == 0:
                ctx.check_deadline()
            n += 1
            rid = Thing(tb, keys.decode_thing_id(k, ns, db, tb))
            yield rid, unpack(raw)


def scan_range(ctx, tb: str, rng: Range) -> PyIterable[Tuple[Thing, dict]]:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    if is_none(rng.beg):
        beg = keys.thing_prefix(ns, db, tb)
    else:
        beg = keys.thing(ns, db, tb, rng.beg)
        if not rng.beg_incl:
            beg += b"\x00"
    if is_none(rng.end):
        end = prefix_end(keys.thing_prefix(ns, db, tb))
    else:
        end = keys.thing(ns, db, tb, rng.end)
        if rng.end_incl:
            end += b"\x00"
    from surrealdb_tpu import accounting

    interval = max(cnf.SCAN_DEADLINE_INTERVAL, 1)
    n = 0
    for chunk in txn.batch(beg, end, cnf.NORMAL_FETCH_SIZE):
        accounting.tally(rows_scanned=len(chunk))
        for k, raw in chunk:
            if n % interval == 0:
                ctx.check_deadline()
            n += 1
            rid = Thing(tb, keys.decode_thing_id(k, ns, db, tb))
            yield rid, unpack(raw)


# ------------------------------------------------------------------ iterator
class Iterator:
    """Runs one data statement's iteration (reference dbs/iterator.rs:117)."""

    def __init__(self, ctx, stm, verb: str):
        self.ctx = ctx
        self.stm = stm
        self.verb = verb
        self.entries: List[Any] = []
        # SELECT results spill to disk past EXTERNAL_SORTING_BUFFER_LIMIT
        # (reference dbs/result.rs:15 Memory|File, dbs/store/file.rs:18);
        # mutating verbs keep plain lists (their outputs are the mutated
        # rows the caller asked back for)
        if verb == "select":
            from surrealdb_tpu.dbs.store import ResultStore

            self.results: Any = ResultStore()
        else:
            self.results = []
        self.cancel_on_limit: Optional[int] = None
        self.mutated = 0  # records actually processed (incl. RETURN NONE)
        # grouped SELECTs collect raw docs; projection happens per group
        self.grouping = verb == "select" and bool(
            getattr(stm, "group", None) or getattr(stm, "group_all", False)
        )
        # SELECTs whose projection invokes ml:: models collect raw docs too,
        # so every scanned row feeds ONE batched device dispatch instead of
        # a per-row forward (BASELINE config 5; reference runs Model::compute
        # per document, core/src/sql/model.rs). Guests / record-access
        # sessions keep the per-row path so per-doc model PERMISSIONS hold.
        self.ml_calls: List[Any] = []
        if verb == "select" and not self.grouping:
            from surrealdb_tpu.iam.check import perms_apply

            if not perms_apply(ctx):
                self.ml_calls = find_model_calls(getattr(stm, "fields", None))
        self.defer_projection = bool(self.ml_calls)
        # set when the (single) planned source already yields rows in the
        # statement's ORDER BY order (IndexOrderPlan) — skips the post-sort
        # and re-enables the LIMIT fast path
        self.order_pushed = False

    def ingest(self, it) -> None:
        self.entries.append(it)

    # -------------------------------------------------------------- run
    def output(self) -> List[Any]:
        ctx, stm, verb = self.ctx, self.stm, self.verb

        # fast-path cancellation: plain SELECT with LIMIT and no
        # reordering/aggregation can stop scanning early (iterator.rs START+LIMIT)
        if (
            verb == "select"
            and stm.limit is not None
            and (not stm.order or self.order_pushed)
            and not stm.group
            and not getattr(stm, "group_all", False)
            and not stm.split
        ):
            try:
                limit = int(stm.limit.compute(ctx))
                start = int(stm.start.compute(ctx)) if stm.start is not None else 0
                self.cancel_on_limit = limit + start
            except (TypeError, ValueError):
                pass

        if (
            verb == "select"
            and getattr(stm, "parallel", False)
            and len(self.entries) > 1
        ):
            self._iterate_parallel()
        else:
            for it in self.entries:
                self._iterate(it)
                if self.cancel_on_limit is not None and len(self.results) >= self.cancel_on_limit:
                    break

        rows = self.results
        if verb == "select":
            rows = self._postprocess(rows)
        elif not isinstance(rows, list):
            rows = rows.to_list()
        return rows

    def _iterate_parallel(self) -> None:
        """PARALLEL SELECT over multiple sources: each source runs on its own
        worker with an isolated child context; device dispatches issued by
        concurrent sources coalesce through the datastore's DispatchQueue.

        TPU-first reading of the reference's PARALLEL thread pipeline
        (core/src/dbs/iterator.rs:569-710): the per-record stages stay
        sequential per source (the kernel batches already cover them); the
        parallelism that pays on this hardware is overlapping *dispatches*.
        Read-only by construction — mutating verbs keep the sequential path.
        """
        from concurrent.futures import ThreadPoolExecutor

        workers = min(len(self.entries), cnf.MAX_CONCURRENT_TASKS)

        def run_entry(entry):
            sub = Iterator(self.ctx._child(), self.stm, self.verb)
            sub.cancel_on_limit = self.cancel_on_limit
            sub._iterate(entry)
            return sub.results

        with ThreadPoolExecutor(max_workers=workers) as pool:
            for res in pool.map(run_entry, self.entries):
                self.results.extend(res)
                if self._full():
                    break

    # -------------------------------------------------------------- dispatch
    def _iterate(self, it) -> None:
        verb = self.verb
        if isinstance(it, IValue):
            self._process_value(it.v)
        elif isinstance(it, IThing):
            self._process_thing(it.t)
        elif isinstance(it, IDefer):
            self._process_defer(it.t)
        elif isinstance(it, IRange):
            for rid, doc in scan_range(self.ctx, it.tb, it.rng):
                self._process_record(rid, doc)
                if self._full():
                    return
        elif isinstance(it, ITable):
            if verb == "upsert":
                # UPSERT over a whole table: if no record was updated (none
                # exist, or the WHERE matched nothing), create the guaranteed
                # record (reference iterator.rs guaranteed-create)
                before = self.mutated
                for rid, doc in scan_table(self.ctx, it.tb):
                    self._process_record(rid, doc)
                if self.mutated == before:
                    self._process_defer(Thing(it.tb), generated_id=True)
                return
            for rid, doc in scan_table(self.ctx, it.tb):
                self._process_record(rid, doc)
                if self._full():
                    return
        elif isinstance(it, IMergeable):
            self._process_mergeable(it)
        elif isinstance(it, IRelatable):
            self._process_relatable(it)
        elif isinstance(it, IIndex):
            self._process_index(it)
        else:
            raise TypeError_(f"unknown iterable {type(it).__name__}")

    def _full(self) -> bool:
        return (
            self.cancel_on_limit is not None
            and len(self.results) >= self.cancel_on_limit
        )

    # -------------------------------------------------------------- per-kind
    def _push(self, v) -> None:
        self.results.append(v)

    def _process_value(self, v) -> None:
        ctx, stm = self.ctx, self.stm
        if self.verb != "select":
            raise InvalidStatementTargetError(format_value(v))
        with ctx.with_doc_value(v) as c:
            if stm.cond is not None and not truthy(stm.cond.compute(c)):
                return
            if self.defer_projection:
                self._push((None, copy_value(v), None))
            elif self.grouping:
                self._push((None, copy_value(v)))
            else:
                self._push(project_fields(c, stm.fields, v, None, stm.value_mode))

    def _process_thing(self, t: Thing) -> None:
        ns, db = self.ctx.ns_db()
        doc = self.ctx.txn().get_record(ns, db, t.tb, t.id)
        if doc is None:
            if self.verb == "upsert":
                self._process_defer(t)
            return
        self._process_record(t, doc)

    def _process_defer(self, t: Thing, generated_id: bool = False) -> None:
        from surrealdb_tpu.doc import pipeline as doc
        from surrealdb_tpu.err import IndexExistsError

        txn = self.ctx.txn()
        sp = txn.savepoint()
        try:
            if self.verb in ("create", "upsert"):
                self._push(doc.process_create(self.ctx, t, self.stm, check_exists=self.verb == "create"))
                self.mutated += 1
            else:
                raise InvalidStatementTargetError(format_value(t))
        except IgnoreError as e:
            if e.mutated:
                self.mutated += 1
        except IndexExistsError as e:
            # a table-level UPSERT (generated id) hitting a unique-index
            # holder retries as an UPDATE of that record (reference
            # RetryWithId, doc/process.rs:24-120); the savepoint discards
            # the half-written create first. An explicit-id UPSERT keeps
            # the error — the user named a DIFFERENT record.
            txn.rollback_to(sp)
            if (
                self.verb != "upsert"
                or not generated_id
                or not isinstance(e.thing, Thing)
            ):
                raise
            ns, db = self.ctx.ns_db()
            existing = txn.get_record(ns, db, e.thing.tb, e.thing.id)
            if existing is None:
                raise
            try:
                self._push(doc.process_update(self.ctx, e.thing, existing, self.stm))
                self.mutated += 1
            except IgnoreError as ig:
                if ig.mutated:
                    self.mutated += 1

    def _process_record(self, rid: Thing, docv: dict, ir=None, skip_cond: bool = False) -> None:
        from surrealdb_tpu.doc import pipeline as doc

        ctx, stm, verb = self.ctx, self.stm, self.verb
        try:
            if verb == "select":
                # per-record PERMISSIONS for record-access / guest sessions
                from surrealdb_tpu.iam.check import (
                    check_table_permission,
                    filter_fields_for_select,
                    perms_apply,
                )

                if rid is not None and perms_apply(ctx):
                    if not check_table_permission(ctx, rid, docv, "select"):
                        return
                    docv = filter_fields_for_select(ctx, rid, docv)
                with ctx.with_doc_value(docv, rid=rid, ir=ir) as c:
                    if (
                        not skip_cond
                        and stm.cond is not None
                        and not truthy(stm.cond.compute(c))
                    ):
                        return
                    if self.grouping or self.defer_projection:
                        self._push((rid, docv, ir) if self.defer_projection else (rid, docv))
                    else:
                        self._push(project_fields(c, stm.fields, docv, rid, stm.value_mode))
            elif verb in ("update", "upsert"):
                self._push(doc.process_update(ctx, rid, docv, stm))
                self.mutated += 1
            elif verb == "delete":
                self._push(doc.process_delete(ctx, rid, docv, stm))
                self.mutated += 1
            else:
                raise TypeError_(f"verb {verb} cannot process a stored record")
        except IgnoreError as e:
            if e.mutated:
                self.mutated += 1

    def _process_mergeable(self, it: IMergeable) -> None:
        from surrealdb_tpu.doc import pipeline as doc
        from surrealdb_tpu.err import IndexExistsError

        txn = self.ctx.txn()
        sp = txn.savepoint()
        try:
            self._push(doc.process_insert(self.ctx, it.t, it.row, self.stm))
        except IgnoreError:
            pass
        except IndexExistsError as e:
            # a UNIQUE INDEX conflict (not an id conflict) on INSERT: roll
            # the half-written record back, then honor IGNORE / ON
            # DUPLICATE KEY UPDATE against the HOLDER record (reference
            # RetryWithId, doc/process.rs:24-120)
            txn.rollback_to(sp)
            if getattr(self.stm, "ignore", False):
                return
            update = getattr(self.stm, "update", None)
            if update is None or not isinstance(e.thing, Thing):
                raise
            ns, db = self.ctx.ns_db()
            existing = txn.get_record(ns, db, e.thing.tb, e.thing.id)
            if existing is None:
                raise
            from surrealdb_tpu.sql.statements import Data

            sub = doc._StmView(
                data=Data("set", update), output=getattr(self.stm, "output", None)
            )
            try:
                self._push(doc.process_update(self.ctx, e.thing, existing, sub))
            except IgnoreError:
                pass

    def _process_relatable(self, it: IRelatable) -> None:
        from surrealdb_tpu.doc import pipeline as doc

        try:
            self._push(
                doc.process_relate(self.ctx, it.e, it.f, it.w, self.stm, row=it.row)
            )
        except IgnoreError:
            pass

    def _process_index(self, it: IIndex) -> None:
        """Index-plan iteration: batches of (rid, doc, ir) from the planner's
        ThingIterator equivalents (reference processor.rs:703-737)."""
        from surrealdb_tpu import telemetry

        # a plan that already applied the full WHERE (columnar scan) tells
        # the per-record stage to skip re-evaluating it
        skip_cond = bool(getattr(it.plan, "cond_satisfied", False))
        n = 0
        try:
            for rid, docv, ir in it.plan.iterate(self.ctx):
                n += 1
                if docv is None:
                    ns, db = self.ctx.ns_db()
                    docv = self.ctx.txn().get_record(ns, db, rid.tb, rid.id)
                    if docv is None:
                        continue
                self._process_record(rid, docv, ir=ir, skip_cond=skip_cond)
                if self._full():
                    return
        finally:
            # candidates the chosen plan actually surfaced — the scan-width
            # signal for "why was this statement slow"
            telemetry.observe_hist(
                "plan_candidates", n, buckets=telemetry.COUNT_BUCKETS
            )

    # -------------------------------------------------------------- ml batching
    def _batched_projection(self, rows: List[Any]) -> List[Any]:
        """Deferred projection for SELECTs containing ml:: calls: every
        scanned row's model input is collected host-side, each distinct call
        runs as ONE batched forward, then the projection is evaluated with
        the per-row results parked as overrides (sql/ast.py ModelCall).

        Rows whose argument expression fails to evaluate fall back to the
        inline per-row path (the call may sit under a conditional branch
        that never reaches it for that row)."""
        from surrealdb_tpu.ml.exec import run_model_batch

        ctx, stm = self.ctx, self.stm
        outputs: dict = {}  # id(call) -> {row_index: value}
        ex = ctx.executor
        # save/restore: a nested deferred SELECT (subquery with its own ml::
        # calls) must not clobber the enclosing projection's overrides
        prev = getattr(ex, "_ml_overrides", None)
        try:
            # innermost-first: a call nested in another call's argument
            # resolves from its overrides while the outer one is collected
            for call in reversed(self.ml_calls):
                per_row: dict = {}
                for i, (rid, docv, ir) in enumerate(rows):
                    ex._ml_overrides = {
                        cid: m[i] for cid, m in outputs.items() if i in m
                    }
                    try:
                        with ctx.with_doc_value(docv, rid=rid, ir=ir) as c:
                            if len(call.args) == 1:
                                per_row[i] = call.args[0].compute(c)
                    except SurrealError:
                        pass
                    finally:
                        ex._ml_overrides = prev
                outputs[id(call)] = run_model_batch(
                    ctx, call.name, call.version, per_row
                )
            out = []
            for i, (rid, docv, ir) in enumerate(rows):
                ex._ml_overrides = {
                    cid: m[i] for cid, m in outputs.items() if i in m
                }
                with ctx.with_doc_value(docv, rid=rid, ir=ir) as c:
                    out.append(
                        project_fields(c, stm.fields, docv, rid, stm.value_mode)
                    )
        finally:
            ex._ml_overrides = prev
        return out

    # -------------------------------------------------------------- postprocess
    def _postprocess(self, rows: Any) -> List[Any]:
        from surrealdb_tpu.dbs.store import ResultStore

        ctx, stm = self.ctx, self.stm
        store = rows if isinstance(rows, ResultStore) else None
        if store is not None and not (
            store.spilled
            and stm.order
            and not self.order_pushed
            and not any(o.rand for o in stm.order)
            and not self.defer_projection
            and not self.grouping
            and not stm.split
        ):
            # no spill (common case) or a shape the external sort can't
            # stream — materialize and run the standard pipeline
            rows = store.to_list()
            store.cleanup()
            store = None
        if store is not None:
            # external merge sort over the spilled result set (reference
            # dbs/store/file.rs:18): runs merge lazily; START+LIMIT slice
            # without materializing the full ordered set
            import itertools

            def keyfunc(row, _order=stm.order):
                out = []
                for o in _order:
                    v = get_path(ctx, row, o.idiom.parts) if isinstance(row, dict) else row
                    k = sort_key(v)
                    out.append(k if o.asc else _RevKey(k))
                return tuple(out)

            start = int(stm.start.compute(ctx)) if stm.start is not None else 0
            limit = (
                int(stm.limit.compute(ctx)) if stm.limit is not None else None
            )
            it = store.sorted_iter(keyfunc)
            if limit is not None:
                rows = list(itertools.islice(it, start, start + limit))
            else:
                rows = list(itertools.islice(it, start, None)) if start else list(it)
            store.cleanup()
        else:
            if self.defer_projection:
                rows = self._batched_projection(rows)
            if self.grouping:
                rows = aggregate_groups(ctx, stm, rows)
            if stm.split:
                rows = apply_split(ctx, rows, stm.split)
            if stm.order and not self.order_pushed:
                rows = apply_order(ctx, rows, stm.order)
            rows = apply_start_limit(ctx, rows, stm.start, stm.limit)
        if stm.omit:
            for row in rows:
                for om in stm.omit:
                    from surrealdb_tpu.sql.path import del_path

                    if isinstance(row, dict):
                        del_path(ctx, row, om.parts)
        if stm.fetch:
            from .fetch import apply_fetch

            rows = apply_fetch(ctx, rows, stm.fetch)
        return rows

# ------------------------------------------------------------------ ml detection
def find_model_calls(fields) -> List[Any]:
    """ModelCall nodes evaluated directly in a projection (not inside
    subquery scope boundaries — those bind a different document)."""
    from surrealdb_tpu.sql.ast import ModelCall, walk_exprs

    found: List[Any] = []

    def visit(node):
        if isinstance(node, ModelCall):
            found.append(node)

    walk_exprs(fields, visit)
    return found


# ------------------------------------------------------------------ projection
def project_fields(ctx, fields, doc_v, rid: Optional[Thing], value_mode: bool):
    """Evaluate the SELECT projection against one document
    (reference: core/src/doc/pluck.rs + sql/field.rs)."""
    if value_mode:
        f = fields[0]
        if f.all:
            return copy_value(doc_v)
        return f.expr.compute(ctx)

    if len(fields) == 1 and fields[0].all:
        return copy_value(doc_v)

    row: dict = {}
    for f in fields:
        if f.all:
            if isinstance(doc_v, dict):
                merged = copy_value(doc_v)
                merged.update(row)
                row = merged
            continue
        v = f.expr.compute(ctx)
        _assign_field(ctx, row, f, v)
    return row


def _assign_field(ctx, row: dict, f, v) -> None:
    if f.alias is not None:
        parts = f.alias.parts if isinstance(f.alias, Idiom) else [PField(str(f.alias))]
        set_path(ctx, row, parts, v)
        return
    expr = f.expr
    if isinstance(expr, Idiom):
        fp = expr.field_path()
        if fp is not None:
            set_path(ctx, row, [PField(n) for n in fp], v)
            return
        row[field_display_name(expr)] = v
        return
    row[field_display_name(expr)] = v


def field_display_name(expr) -> str:
    """Default output key for an expression field (reference Idiom::simplify)."""
    if isinstance(expr, FunctionCall):
        return expr.name
    if isinstance(expr, Idiom):
        return repr(expr)
    return repr(expr)


class _RevKey:
    """Inverts comparison for DESC components of a composite external-sort
    key (heapq.merge needs ONE ascending keyfunc across all runs)."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v


# ------------------------------------------------------------------ split/order/limit
def apply_split(ctx, rows: List[Any], split_idioms) -> List[Any]:
    for idiom in split_idioms:
        out = []
        for row in rows:
            if not isinstance(row, dict):
                out.append(row)
                continue
            v = get_path(ctx, row, idiom.parts)
            if isinstance(v, list):
                for item in v:
                    r2 = copy_value(row)
                    set_path(ctx, r2, idiom.parts, item)
                    out.append(r2)
            else:
                out.append(row)
        rows = out
    return rows


def apply_order(ctx, rows: List[Any], order_items) -> List[Any]:
    if any(o.rand for o in order_items):
        rows = list(rows)
        random.shuffle(rows)
        return rows

    # stable multi-key sort honoring per-key direction: sort by keys in
    # reverse priority order
    out = list(rows)
    for o in reversed(order_items):

        def single(row, o=o):
            v = get_path(ctx, row, o.idiom.parts) if isinstance(row, dict) else row
            return sort_key(v)

        out.sort(key=single, reverse=not o.asc)
    return out


def apply_start_limit(ctx, rows: List[Any], start_e, limit_e) -> List[Any]:
    start = 0
    if start_e is not None:
        start = _as_int(start_e.compute(ctx), "START")
    if limit_e is not None:
        limit = _as_int(limit_e.compute(ctx), "LIMIT")
        return rows[start : start + limit]
    return rows[start:] if start else rows


def _as_int(v, clause: str) -> int:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise TypeError_(f"Found {format_value(v)} but the {clause} clause expects a number")
    return int(v)


# ------------------------------------------------------------------ grouping
# Aggregate function names handled over whole groups
# (reference: core/src/dbs/group.rs OptimisedAggregate :320).
_AGGREGATES = {
    "count",
    "math::sum",
    "math::mean",
    "math::min",
    "math::max",
    "math::stddev",
    "math::variance",
    "math::median",
    "time::min",
    "time::max",
    "array::group",
    "array::distinct",
    "array::flatten",
    "array::concat",
    "array::first",
    "array::last",
}


def aggregate_groups(ctx, stm, docs: List[Tuple[Optional[Thing], Any]]) -> List[Any]:
    """Group raw documents and evaluate the projection with aggregate
    semantics (reference: core/src/dbs/group.rs GroupsCollector)."""
    group_idioms = stm.group or []
    groups: dict = {}
    order: List[Any] = []
    for rid, docv in docs:
        if group_idioms:
            with ctx.with_doc_value(docv, rid=rid) as c:
                key_vals = tuple(
                    _hashable(g.compute(c)) for g in group_idioms
                )
        else:
            key_vals = ()
        if key_vals not in groups:
            groups[key_vals] = []
            order.append(key_vals)
        groups[key_vals].append((rid, docv))

    out = []
    for key_vals in order:
        members = groups[key_vals]
        row: dict = {}
        for f in stm.fields:
            if f.all:
                # `*` in a grouped select: merge the first member
                first = members[0][1]
                if isinstance(first, dict):
                    merged = copy_value(first)
                    merged.update(row)
                    row = merged
                continue
            v = _eval_grouped(ctx, f.expr, members)
            _assign_field(ctx, row, f, v)
        out.append(row)
    return out


def _hashable(v):
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def _eval_grouped(ctx, expr, members: List[Tuple[Optional[Thing], Any]]):
    if isinstance(expr, FunctionCall) and expr.name in _AGGREGATES:
        return _eval_aggregate(ctx, expr, members)
    # non-aggregate: evaluate on the first member of the group
    rid, docv = members[0]
    with ctx.with_doc_value(docv, rid=rid) as c:
        return expr.compute(c)


def _eval_aggregate(ctx, call: FunctionCall, members):
    name = call.name
    if name == "count" and not call.args:
        return len(members)

    # evaluate the argument per member
    vals = []
    for rid, docv in members:
        with ctx.with_doc_value(docv, rid=rid) as c:
            vals.append(call.args[0].compute(c))

    if name == "count":
        return sum(1 for v in vals if truthy(v))

    nums = [v for v in vals if isinstance(v, (int, float)) and not isinstance(v, bool)]
    if name == "math::sum":
        return sum(nums)
    if name == "math::mean":
        return (sum(nums) / len(nums)) if nums else NONE
    if name == "math::min":
        return min(nums, default=NONE)
    if name == "math::max":
        return max(nums, default=NONE)
    if name == "math::stddev":
        return _stddev(nums)
    if name == "math::variance":
        return _variance(nums)
    if name == "math::median":
        if not nums:
            return NONE
        s = sorted(nums)
        n = len(s)
        return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2
    if name == "time::min":
        present = [v for v in vals if not is_nullish(v)]
        return min(present, key=sort_key, default=NONE)
    if name == "time::max":
        present = [v for v in vals if not is_nullish(v)]
        return max(present, key=sort_key, default=NONE)
    if name == "array::group":
        out = []
        for v in vals:
            items = v if isinstance(v, list) else [v]
            for x in items:
                if not any(value_eq(x, y) for y in out):
                    out.append(x)
        return out
    if name == "array::distinct":
        out = []
        for v in vals:
            if not any(value_eq(v, y) for y in out):
                out.append(v)
        return out
    if name == "array::flatten":
        out = []
        for v in vals:
            if isinstance(v, list):
                out.extend(v)
            else:
                out.append(v)
        return out
    if name == "array::concat":
        out = []
        for v in vals:
            if isinstance(v, list):
                out.extend(v)
            else:
                out.append(v)
        return out
    if name == "array::first":
        return vals[0] if vals else NONE
    if name == "array::last":
        return vals[-1] if vals else NONE
    raise TypeError_(f"unknown aggregate {name}")


def _variance(nums):
    if len(nums) < 2:
        return NONE if not nums else 0.0
    m = sum(nums) / len(nums)
    return sum((x - m) ** 2 for x in nums) / (len(nums) - 1)


def _stddev(nums):
    v = _variance(nums)
    if isinstance(v, (int, float)):
        return v**0.5
    return v
