"""Fingerprint-keyed plan & pipeline cache: serve hot statement shapes
without re-parsing or re-planning.

The workload statistics plane (stats.py) proved that production traffic
collapses onto a small set of statement SHAPES — the PR 15 fingerprint.
Every execution still paid the full cold ladder: parse, plan probe
(`txn.all_tb_indexes`), pipeline lowering (`ops/pipeline.analyze_select`),
predicate compile (`ops/predicates.compile_where`). This module caches all
of it per fingerprint and serves hot shapes from memory:

- **Template AST.** The first parses of a shape install the parsed Query
  as a shared template. Literal slots are parameterized (`ast.SlotLiteral`)
  so `WHERE age > 30` and `WHERE age > 40` — and the `$param` spelling of
  the same shape — share one entry; the active execution's values ride the
  per-query Executor (`executor.slot_values`), never the shared nodes.
- **Dispatch skeleton.** Which `dbs/stmt_exec.select_compute` front
  resolved the statement (ml / count / pipeline / plan), so warm serves
  skip the fronts that declined cold.
- **Pipeline lowering.** The resolved `ops/pipeline.Lowering` — grouped
  shape or order specs, projection, and the compiled `ops/predicates.py`
  mask *program*. Mask content still binds per execution: the compiled
  predicate is `rebind()`-ed against the live context on every serve.
- **Planner schema prefetch.** The `all_tb_indexes` probe result per
  (ns, db, tb), so `idx/planner._build_index_plan` skips its per-execution
  KV scan.

Correctness is validation-on-serve, NEVER TTL:

- **Binding is verified, not assumed.** A new text that lex-matches a
  parameterized variant is parsed ONCE and structurally compared against
  the bound template (`_ast_equal`). Only after `_VERIFY_TRUST` distinct
  texts verify byte-identically does the variant serve on lex alone; a
  single mismatch demotes it to exact-digest serving forever.
- **Schema/index generation.** Routes record a per-(ns, db) generation.
  DDL (`DEFINE`/`REMOVE`/`ALTER`/`REBUILD`, and the async index builder's
  ready flip) brackets itself with `ddl_begin`/`ddl_end`: the begin bump
  invalidates every pre-DDL artifact, installs are refused while a DDL is
  in flight, and the end bump invalidates anything raced in between — no
  window in which a plan built on the old schema can be served against
  the new one.
- **Tenant/session scope.** Route artifacts are keyed by
  (ns, db, auth level, roles, access, record id): a cached plan never
  leaks across tenants or privilege levels. The template AST itself is
  scope-free (it is just the parse).
- **Cluster epoch.** Routes record the membership epoch seen at install;
  `note_epoch` invalidates them all when the ring changes.
- **Mirror serve state.** A cached pipeline serve that the mirror
  declines drops the route (cause `mirror`) and falls back to the cold
  ladder, which re-resolves and re-installs.
- **Plan-mix flips.** A PR 15 plan-flip (`stats.record`) evicts the
  flipped fingerprint's whole entry (cause `flip`) — visible as a
  `plan_cache.evict` event and a `plan_cache_invalidations` count.
- **Periodic revalidation.** Every `_REVALIDATE_EVERY` serves a route
  declines once so the cold ladder re-derives it — insurance against
  decisions pinned forever (a cached row route never re-attempting a
  newly serveable mirror).

Every mutation goes through this class — the single write door graftlint
GL015 enforces statically. Knobs: `SURREAL_PLAN_CACHE` (on/off),
`SURREAL_PLAN_CACHE_CAP` (entries), `SURREAL_PLAN_CACHE_MIN_HITS`
(observations before a shape is installed).

Lock discipline: `plan_cache.store` is a leaf-style observability lock
(locks.HIERARCHY level 85). Telemetry counters and `plan_cache.evict`
events are emitted AFTER release, mirroring stats.py.
"""

from __future__ import annotations

import hashlib
import time
import weakref
from collections import Counter, OrderedDict, deque
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from surrealdb_tpu.utils import locks as _locks

_DIGEST_CAP = 32  # distinct literal combinations remembered per variant
_VARIANT_CAP = 4  # arity/spelling variants kept per fingerprint entry
_SCOPE_CAP = 8  # tenant/session scopes with routes per variant
_VERIFY_TRUST = 4  # verified lex-serves before a variant skips the parse
_REVALIDATE_EVERY = 64  # serves between forced cold re-resolutions
_EVLOG_CAP = 64  # recent evictions kept for the advisor's thrash view


class Served(NamedTuple):
    """One warm AST serve: the shared template Query plus this
    execution's slot bindings (None when the variant is unparameterized)."""

    query: Any
    slot_values: Optional[Tuple[Any, ...]]
    fp: str


class _Route:
    """One tenant scope's resolved dispatch for a template statement."""

    __slots__ = ("front", "lowering", "gen", "epoch", "serves", "installed")

    def __init__(self, front: str, gen: Tuple, epoch: Any):
        self.front = front
        self.lowering = None  # ops/pipeline.Lowering for front == "pipeline"
        self.gen = gen  # (ns, db, generation) captured at statement start
        self.epoch = epoch
        self.serves = 0
        self.installed = time.time()


class _Variant:
    """One spelling of a fingerprint: a shared template AST plus the
    token signature that decides whether a new text can bind into it."""

    __slots__ = (
        "query", "stmt", "kinds", "fixed", "slot_idx", "digests",
        "routes", "parameterized", "trust", "text",
    )

    def __init__(self, query, kinds, fixed, slot_idx, parameterized, text):
        self.query = query
        self.stmt = query.statements[0]
        self.kinds = kinds  # signature token kinds, source order
        self.fixed = fixed  # ((token_idx, value), ...) must match verbatim
        self.slot_idx = slot_idx  # token indices bound to SlotLiteral slots
        self.digests: "OrderedDict[str, Optional[Tuple]]" = OrderedDict()
        self.routes: "OrderedDict[Tuple, _Route]" = OrderedDict()
        self.parameterized = parameterized
        self.trust = 0  # verified lex-serves; >= _VERIFY_TRUST skips verify
        self.text = text  # first-seen spelling (views/debug only)


class _Entry:
    """One fingerprint's cached variants and serve counters."""

    __slots__ = ("fp", "variants", "hits", "route_hits", "misses",
                 "invalidations", "churn", "installed_ts")

    def __init__(self, fp: str):
        self.fp = fp
        self.variants: List[_Variant] = []
        self.hits = 0
        self.route_hits = 0
        self.misses = 0
        self.invalidations = 0
        self.churn = 0  # variant capacity evictions (thrash guard)
        self.installed_ts = time.time()


# statements whose ASTs are safe and worth sharing: no DDL (those bump
# generations instead), no LIVE/KILL (a live query retains its AST past
# the execution, where slot bindings would no longer ride the executor),
# no transaction control, no EXPLAIN (stmt_exec mutate-restores it)
def _cacheable(stm) -> bool:
    from surrealdb_tpu.sql import statements as S

    if not isinstance(
        stm,
        (
            S.SelectStatement, S.CreateStatement, S.UpdateStatement,
            S.DeleteStatement, S.InsertStatement, S.RelateStatement,
            S.ReturnStatement,
        ),
    ):
        return False
    if isinstance(stm, S.SelectStatement) and (
        stm.explain or stm.explain_full or stm.explain_analyze
    ):
        return False
    return True


def _stmt_key(text: str) -> str:
    """Canonical single-statement text: what the parser records as the
    statement's source (`Query.sources`) and what stats.fingerprint keys
    on — leading/trailing separators stripped so `SELECT 1` and
    `SELECT 1;` share the entry the flip hook will evict."""
    return text.strip().strip(";").strip()


def _digest(key: str) -> str:
    return hashlib.blake2b(key.encode("utf-8"), digest_size=8).hexdigest()


def _fixed_eq(a: Any, b: Any) -> bool:
    """Strict signature equality: same concrete type AND equal value
    (int 5 never matches float 5.0 — binding the wrong numeric flavor
    changes results). Regex-ish values compare by pattern (fresh lex
    runs produce distinct objects)."""
    if a is b:
        return True
    if type(a) is not type(b):
        return False
    pa = getattr(a, "pattern", None)
    if pa is not None:
        return pa == getattr(b, "pattern", None)
    try:
        return bool(a == b)
    except Exception:
        return False


# ------------------------------------------------------------------ AST walk
def _is_sql_node(o: Any) -> bool:
    return type(o).__module__.startswith("surrealdb_tpu.sql")


def _slot_names(o: Any) -> List[str]:
    names: List[str] = []
    for klass in type(o).__mro__:
        names.extend(getattr(klass, "__slots__", ()))
    return names


def _collect_literal_sites(root) -> List[Tuple[Any, Any, Any]]:
    """Every exact-type ast.Literal reachable from `root`, as
    (container, key, node) so the node can be swapped for a SlotLiteral.
    Literals inside tuples/sets are unreplaceable and not collected —
    their tokens stay fixed in the signature, which is always sound."""
    from surrealdb_tpu.sql import ast as A

    sites: List[Tuple[Any, Any, Any]] = []
    seen: set = set()

    def consider(container, key, v) -> bool:
        if type(v) is A.Literal:
            sites.append((container, key, v))
            return True
        return False

    def walk(o) -> None:
        oid = id(o)
        if oid in seen:
            return
        seen.add(oid)
        if isinstance(o, list):
            for i, v in enumerate(o):
                if not consider(o, i, v):
                    walk(v)
        elif isinstance(o, dict):
            for k, v in list(o.items()):
                if not consider(o, k, v):
                    walk(v)
        elif isinstance(o, (tuple, set, frozenset)):
            for v in o:
                walk(v)
        elif _is_sql_node(o):
            for name in _slot_names(o):
                try:
                    v = getattr(o, name)
                except AttributeError:
                    continue
                if not consider(o, name, v):
                    walk(v)

    walk(root)
    return sites


def _ast_equal(tmpl, fresh, slot_values: Tuple[Any, ...]) -> bool:
    """Structural equality of the bound template against a fresh parse —
    the serve-time proof that slot binding reproduces exactly what the
    parser would have built for the new text."""
    from surrealdb_tpu.sql import ast as A

    def eq(a, b) -> bool:
        if isinstance(a, A.SlotLiteral):
            bound = (
                slot_values[a.slot]
                if a.slot < len(slot_values)
                else a.value
            )
            return type(b) is A.Literal and _fixed_eq(bound, b.value)
        if type(a) is not type(b):
            return False
        if isinstance(a, list) or isinstance(a, tuple):
            return len(a) == len(b) and all(eq(x, y) for x, y in zip(a, b))
        if isinstance(a, dict):
            if a.keys() != b.keys():
                return False
            return all(eq(v, b[k]) for k, v in a.items())
        if _is_sql_node(a):
            for name in _slot_names(a):
                try:
                    va, vb = getattr(a, name), getattr(b, name)
                except AttributeError:
                    return False
                if not eq(va, vb):
                    return False
            return True
        return _fixed_eq(a, b)

    return eq(tmpl, fresh)


def _parameterize(text: str, query) -> Optional[_Variant]:
    """Build a variant for `query` (parsed from `text`): lex the
    signature tokens, match bindable token values 1:1 against replaceable
    Literal nodes, swap matches for SlotLiterals. Any ambiguity — a
    duplicated value among tokens or among nodes, a token folded into a
    non-Literal (record ids, negative-number folding) — demotes that
    token to a fixed position; a variant with no slots still serves any
    literal-identical respelling (case/whitespace) plus its routes."""
    from surrealdb_tpu.sql import ast as A
    from surrealdb_tpu.syn import parser as _parser

    lexed = _parser.lex_literal_slots(text)
    if lexed is None:
        return None
    kinds, values = lexed
    sites = _collect_literal_sites(query)
    taken: set = set()
    slot_sites: List[Tuple[int, Tuple[Any, Any, Any]]] = []
    fixed: List[Tuple[int, Any]] = []
    bindable = [
        i for i, k in enumerate(kinds) if k in _parser.BINDABLE_TOKEN_KINDS
    ]
    for i in bindable:
        v = values[i]
        dup = any(j != i and _fixed_eq(values[j], v) for j in bindable)
        matches = [
            s for s in sites
            if id(s[2]) not in taken and _fixed_eq(s[2].value, v)
        ]
        if dup or len(matches) != 1:
            fixed.append((i, v))
            continue
        taken.add(id(matches[0][2]))
        slot_sites.append((i, matches[0]))
    for i, k in enumerate(kinds):
        if k not in _parser.BINDABLE_TOKEN_KINDS:
            fixed.append((i, values[i]))
    fixed.sort()
    for slot, (_, (container, key, node)) in enumerate(slot_sites):
        sl = A.SlotLiteral(slot, node.value)
        if isinstance(container, list):
            container[key] = sl
        elif isinstance(container, dict):
            container[key] = sl
        else:
            setattr(container, key, sl)
    return _Variant(
        query,
        kinds,
        tuple(fixed),
        tuple(i for i, _ in slot_sites),
        bool(slot_sites),
        _stmt_key(text)[:200],
    )


def _scope_key(session) -> Tuple:
    """The tenant/session scope a route is valid for — a cached plan must
    never leak across namespaces, databases, or privilege levels."""
    a = getattr(session, "auth", None)
    return (
        getattr(session, "ns", None),
        getattr(session, "db", None),
        getattr(a, "level", None),
        tuple(getattr(a, "roles", ()) or ()),
        getattr(a, "access", None),
        str(getattr(a, "rid", None)),
    )


# ------------------------------------------------------------------ cache
class PlanCache:
    """Per-datastore plan & pipeline cache. All state behind `_lock`
    (`plan_cache.store`, locks.HIERARCHY 85); every mutation goes through
    the public methods below — graftlint GL015's single write door."""

    def __init__(self, ds):
        from surrealdb_tpu import cnf

        self.enabled = bool(getattr(cnf, "PLAN_CACHE", True))
        self._cap = max(int(getattr(cnf, "PLAN_CACHE_CAP", 512)), 8)
        self._min_hits = max(int(getattr(cnf, "PLAN_CACHE_MIN_HITS", 2)), 1)
        self._ds = weakref.ref(ds)
        self._lock = _locks.Lock("plan_cache.store")
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._warm: "OrderedDict[str, int]" = OrderedDict()  # fp -> observes
        self._by_stmt: Dict[int, Tuple[str, _Variant]] = {}
        self._index_defs: "OrderedDict[Tuple, Tuple[Tuple, list]]" = (
            OrderedDict()
        )  # (ns, db, tb) -> (gen token, raw defs)
        self._gen: Dict[Tuple, int] = {}  # (ns, db) -> schema generation
        self._inflight: Dict[Tuple, int] = {}  # (ns, db) -> DDLs in flight
        self._epoch: Any = None  # cluster membership epoch, None standalone
        self._timing: "OrderedDict[str, Dict[str, float]]" = OrderedDict()
        self._hits = {"ast": 0, "route": 0}
        self._misses: Counter = Counter()
        self._invalidations: Counter = Counter()
        self._verifies = {"ok": 0, "failed": 0}
        self._evlog: deque = deque(maxlen=_EVLOG_CAP)
        _caches.add(self)

    # ------------------------------------------------------- AST serve
    def fetch(self, text: str) -> Optional[Served]:
        """The parser cache-front (ds.execute_local). Returns a warm
        Served or None (caller parses cold and calls observe())."""
        if not self.enabled:
            return None
        from surrealdb_tpu import stats

        t0 = time.perf_counter()
        key = _stmt_key(text)
        if not key or ";" in key:
            return None  # empty or multi-statement: never cached
        fp, _ = stats.fingerprint(key)
        dg = _digest(key)
        with self._lock:
            entry = self._entries.get(fp)
            if entry is None:
                self._misses["cold"] += 1
                out = None
            else:
                self._entries.move_to_end(fp)
                out = self._serve_digest(entry, dg)
        if out is None and entry is not None:
            out = self._serve_lexed(entry, fp, key, dg)
        if out is not None:
            self._note_timing(fp, "parse", (time.perf_counter() - t0) * 1e6, True)
            self._inc_hit("ast")
        elif entry is None:
            self._inc_miss("cold")
        return out

    def _serve_digest(self, entry: _Entry, dg: str) -> Optional[Served]:
        """Exact-text hit: no lexing, no binding derivation. Lock held."""
        for v in entry.variants:
            if dg in v.digests:
                v.digests.move_to_end(dg)
                entry.hits += 1
                self._hits["ast"] += 1
                return Served(v.query, v.digests[dg], entry.fp)
        return None

    def _serve_lexed(
        self, entry: _Entry, fp: str, key: str, dg: str
    ) -> Optional[Served]:
        """New spelling of a cached shape: lex, match a variant's
        signature, bind slot values — verifying against a fresh parse
        until the variant has earned trust."""
        from surrealdb_tpu.syn import parser as _parser

        lexed = _parser.lex_literal_slots(key)
        if lexed is None:
            with self._lock:
                entry.misses += 1
                self._misses["unlexable"] += 1
            self._inc_miss("unlexable")
            return None
        kinds, values = lexed
        with self._lock:
            match: Optional[_Variant] = None
            for v in entry.variants:
                if v.kinds == kinds and all(
                    _fixed_eq(values[i], fv) for i, fv in v.fixed
                ):
                    match = v
                    break
            if match is None or (not match.parameterized and match.digests):
                # unparameterized variants serve by digest only — a new
                # spelling means a genuinely different statement
                entry.misses += 1
                self._misses["variant"] += 1
                cause = "variant"
            else:
                slots = tuple(values[i] for i in match.slot_idx)
                trusted = match.trust >= _VERIFY_TRUST
        if match is None or (not match.parameterized and match.digests):
            self._inc_miss(cause)
            return None
        if not trusted and not self._verify(match, key, slots):
            return None
        with self._lock:
            entry.hits += 1
            self._hits["ast"] += 1
            if len(match.digests) >= _DIGEST_CAP:
                match.digests.popitem(last=False)
            match.digests[dg] = slots or None
        return Served(match.query, slots or None, fp)

    def _verify(self, variant: _Variant, key: str, slots: Tuple) -> bool:
        """Parse `key` fresh and prove the bound template reproduces it.
        Success builds trust; ONE failure demotes the variant to
        exact-digest serving for good (cause `verify`)."""
        from surrealdb_tpu.syn import parse_query

        try:
            fresh = parse_query(key)
        except Exception:
            return False
        ok = len(fresh.statements) == 1 and _ast_equal(
            variant.stmt, fresh.statements[0], slots
        )
        with self._lock:
            if ok:
                variant.trust += 1
                self._verifies["ok"] += 1
            else:
                variant.parameterized = False
                variant.trust = 0
                self._verifies["failed"] += 1
                self._invalidations["verify"] += 1
        if not ok:
            self._inc_invalidation("verify")
            self._inc_miss("verify")
        return ok

    def observe(self, text: str, query, parse_us: float) -> None:
        """The cold-parse report (ds.execute_local): counts the shape and,
        once it has been seen `_MIN_HITS` times, installs the parsed
        query as a shared template (parameterized in place — SlotLiteral
        defaults keep this very execution's values)."""
        if not self.enabled:
            return
        from surrealdb_tpu import stats

        if len(query.statements) != 1 or not _cacheable(query.statements[0]):
            return
        key = _stmt_key(text)
        if not key or ";" in key:
            return
        fp, _ = stats.fingerprint(key)
        self._note_timing(fp, "parse", parse_us, False)
        with self._lock:
            n = self._warm.get(fp, 0) + 1
            self._warm[fp] = n
            self._warm.move_to_end(fp)
            while len(self._warm) > self._cap * 4:
                self._warm.popitem(last=False)
            if n < self._min_hits:
                return
        variant = _parameterize(text, query)
        if variant is None:
            return
        evicted: List[Tuple[str, str]] = []
        dg = _digest(key)
        with self._lock:
            entry = self._entries.get(fp)
            if entry is None:
                entry = self._entries[fp] = _Entry(fp)
            self._entries.move_to_end(fp)
            for v in entry.variants:
                if v.kinds == variant.kinds and len(v.fixed) == len(
                    variant.fixed
                ) and all(
                    i == j and _fixed_eq(a, b)
                    for (i, a), (j, b) in zip(v.fixed, variant.fixed)
                ):
                    # raced install of the same spelling: keep the winner
                    return
            if entry.churn > 8 and not variant.parameterized:
                # a high-cardinality unparameterizable shape (distinct
                # record ids, folded literals): installing yet another
                # exact-text variant would just keep thrashing the slots
                return
            while len(entry.variants) >= _VARIANT_CAP:
                old = entry.variants.pop(0)
                self._drop_variant(old)
                self._invalidations["capacity"] += 1
                entry.churn += 1
            entry.variants.append(variant)
            variant.digests[dg] = tuple(self._defaults_of(variant)) or None
            self._by_stmt[id(variant.stmt)] = (fp, variant)
            while len(self._entries) > self._cap:
                old_fp, old_e = self._entries.popitem(last=False)
                for v in old_e.variants:
                    self._drop_variant(v)
                self._invalidations["capacity"] += 1
                self._evlog.append(
                    {"fp": old_fp, "cause": "capacity", "ts": time.time()}
                )
                evicted.append((old_fp, "capacity"))
        for efp, cause in evicted:
            self._emit_evict(efp, cause)

    @staticmethod
    def _defaults_of(variant: _Variant) -> List[Any]:
        """The installing text's own slot values (the SlotLiteral
        defaults), so its digest serves without re-deriving bindings."""
        from surrealdb_tpu.sql import ast as A

        out: Dict[int, Any] = {}

        def walk(o, seen):
            if id(o) in seen:
                return
            seen.add(id(o))
            if isinstance(o, A.SlotLiteral):
                out[o.slot] = o.value
                return
            if isinstance(o, (list, tuple, set, frozenset)):
                for v in o:
                    walk(v, seen)
            elif isinstance(o, dict):
                for v in o.values():
                    walk(v, seen)
            elif _is_sql_node(o):
                for name in _slot_names(o):
                    try:
                        walk(getattr(o, name), seen)
                    except AttributeError:
                        pass

        walk(variant.stmt, set())
        return [out[k] for k in sorted(out)]

    def _drop_variant(self, v: _Variant) -> None:
        """Lock held: detach a variant's identity-map entry and routes."""
        self._by_stmt.pop(id(v.stmt), None)
        v.routes.clear()

    # ------------------------------------------------------- route serve
    def _route_for(self, ctx, stm) -> Optional[Tuple[str, _Variant, _Route]]:
        """Lock held by caller? No — takes the lock itself. Resolves the
        (fp, variant, route) for `stm` IF stm is a cached template
        statement and every validation stamp still matches."""
        o = self._by_stmt.get(id(stm))
        if o is None or o[1].stmt is not stm:
            return None
        fp, variant = o
        scope = _scope_key(getattr(ctx.executor, "session", None))
        route = variant.routes.get(scope)
        if route is None:
            return None
        ns, db, gen = route.gen
        if self._gen.get((ns, db), 0) != gen or self._inflight.get((ns, db)):
            del variant.routes[scope]
            self._invalidations["ddl"] += 1
            return ("ddl", variant, route)
        if route.epoch != self._epoch:
            del variant.routes[scope]
            self._invalidations["epoch"] += 1
            return ("epoch", variant, route)
        route.serves += 1
        if route.serves % _REVALIDATE_EVERY == 0:
            self._invalidations["revalidate"] += 1
            return ("revalidate", variant, route)
        return (fp, variant, route)

    def front_for(self, ctx, stm) -> Optional[str]:
        """The dispatch skeleton (stmt_exec.select_compute): which front
        resolved this shape cold, or None to run the full ladder."""
        if not self.enabled:
            return None
        cause = None
        with self._lock:
            res = self._route_for(ctx, stm)
            if res is None:
                return None
            tag, variant, route = res
            if tag in ("ddl", "epoch", "revalidate"):
                cause = tag
                front = None
            else:
                front = route.front
                self._hits["route"] += 1
                e = self._entries.get(tag)
                if e is not None:
                    e.route_hits += 1
        if cause is not None:
            self._inc_invalidation(cause)
            return None
        self._inc_hit("route")
        return front

    def note_front(self, ctx, stm, front: str) -> None:
        """Cold-ladder report: record which front resolved the template
        statement, under the generation token captured at statement
        start (refused while a DDL is in flight)."""
        if not self.enabled:
            return
        token = getattr(ctx.executor, "plan_gen", None)
        if token is None:
            return
        ns, db, gen = token
        with self._lock:
            o = self._by_stmt.get(id(stm))
            if o is None or o[1].stmt is not stm:
                return
            if (
                self._gen.get((ns, db), 0) != gen
                or self._inflight.get((ns, db))
            ):
                return
            variant = o[1]
            scope = _scope_key(getattr(ctx.executor, "session", None))
            route = variant.routes.get(scope)
            if route is None or route.front != front:
                route = _Route(front, token, self._epoch)
                while len(variant.routes) >= _SCOPE_CAP:
                    variant.routes.popitem(last=False)
                variant.routes[scope] = route
            else:
                route.gen = token
                route.epoch = self._epoch
                variant.routes.move_to_end(scope)

    def lowering_for(self, ctx, stm):
        """The cached ops/pipeline.Lowering for this template statement
        and scope, already stamp-validated — or None (cold analyze)."""
        if not self.enabled:
            return None
        with self._lock:
            o = self._by_stmt.get(id(stm))
            if o is None or o[1].stmt is not stm:
                return None
            scope = _scope_key(getattr(ctx.executor, "session", None))
            route = o[1].routes.get(scope)
            if route is None or route.front != "pipeline":
                return None
            ns, db, gen = route.gen
            if (
                self._gen.get((ns, db), 0) != gen
                or self._inflight.get((ns, db))
                or route.epoch != self._epoch
            ):
                return None  # front_for already counted the invalidation
            return route.lowering

    def install_lowering(self, ctx, stm, lowering) -> None:
        """Attach the cold-analyzed Lowering to the statement's pipeline
        route (note_front has just recorded the front)."""
        if not self.enabled:
            return
        with self._lock:
            o = self._by_stmt.get(id(stm))
            if o is None or o[1].stmt is not stm:
                return
            scope = _scope_key(getattr(ctx.executor, "session", None))
            route = o[1].routes.get(scope)
            if route is not None and route.front == "pipeline":
                route.lowering = lowering

    def install_pipeline(self, ctx, stm, lowering) -> None:
        """Cold pipeline resolve: record the front AND attach the
        Lowering in one door (ops/pipeline.run_pipeline)."""
        self.note_front(ctx, stm, "pipeline")
        self.install_lowering(ctx, stm, lowering)

    def drop_route(self, ctx, stm, cause: str) -> None:
        """A validated serve was declined downstream (the mirror said
        no): drop the route so the cold ladder re-resolves next time."""
        dropped = False
        with self._lock:
            o = self._by_stmt.get(id(stm))
            if o is not None and o[1].stmt is stm:
                scope = _scope_key(getattr(ctx.executor, "session", None))
                if o[1].routes.pop(scope, None) is not None:
                    self._invalidations[cause] += 1
                    dropped = True
        if dropped:
            self._inc_invalidation(cause)

    # ------------------------------------------------------- planner defs
    def index_defs_for(self, ctx, ns, db, tb) -> Optional[list]:
        """The cached raw `all_tb_indexes` probe for (ns, db, tb), valid
        only at the current schema generation with no DDL in flight."""
        if not self.enabled:
            return None
        key = (ns, db, tb)
        with self._lock:
            got = self._index_defs.get(key)
            if got is None:
                return None
            (gns, gdb, gen), defs = got
            if self._gen.get((gns, gdb), 0) != gen or self._inflight.get(
                (gns, gdb)
            ):
                del self._index_defs[key]
                self._invalidations["ddl"] += 1
                return None
            self._index_defs.move_to_end(key)
        return defs

    def install_index_defs(self, ctx, ns, db, tb, defs: list) -> None:
        token = getattr(
            getattr(ctx, "executor", None), "plan_gen", None
        ) or (ns, db, self._gen.get((ns, db), 0))
        tns, tdb, gen = token
        if (tns, tdb) != (ns, db):
            return  # a USE switched scope mid-statement: don't stamp-mix
        with self._lock:
            if self._gen.get((ns, db), 0) != gen or self._inflight.get(
                (ns, db)
            ):
                return
            self._index_defs[(ns, db, tb)] = (token, list(defs))
            while len(self._index_defs) > self._cap:
                self._index_defs.popitem(last=False)

    # ------------------------------------------------------- invalidation
    def gen_token(self, ns, db) -> Tuple:
        """The generation token an executor captures at statement start;
        installs made under a stale or in-flight token are refused, which
        closes the DDL-commit-to-bump race."""
        if self._inflight.get((ns, db)):
            return (ns, db, -1)  # never matches: a DDL is in flight
        return (ns, db, self._gen.get((ns, db), 0))

    def ddl_begin(self, ns, db) -> None:
        """Bracket a schema change: bump the generation (invalidating
        every pre-DDL artifact lazily) and refuse installs until
        ddl_end's second bump covers anything raced in between."""
        with self._lock:
            self._gen[(ns, db)] = self._gen.get((ns, db), 0) + 1
            self._inflight[(ns, db)] = self._inflight.get((ns, db), 0) + 1

    def ddl_end(self, ns, db) -> None:
        with self._lock:
            self._gen[(ns, db)] = self._gen.get((ns, db), 0) + 1
            n = self._inflight.get((ns, db), 0) - 1
            if n > 0:
                self._inflight[(ns, db)] = n
            else:
                self._inflight.pop((ns, db), None)
        self._inc_invalidation("ddl")

    def bump_generation(self, ns, db) -> None:
        """One-shot generation bump for schema changes that are not
        statement-bracketed (the async index builder's ready flip)."""
        with self._lock:
            self._gen[(ns, db)] = self._gen.get((ns, db), 0) + 1
        self._inc_invalidation("ddl")

    def on_plan_flip(self, fp: str) -> None:
        """stats.record detected a plan-mix flip: the shape's cached
        decision is now suspect — evict the whole entry."""
        with self._lock:
            entry = self._entries.pop(fp, None)
            if entry is not None:
                for v in entry.variants:
                    self._drop_variant(v)
                self._invalidations["flip"] += 1
                self._evlog.append(
                    {"fp": fp, "cause": "flip", "ts": time.time()}
                )
        if entry is not None:
            self._inc_invalidation("flip")
            self._emit_evict(fp, "flip")

    def note_epoch(self, epoch) -> None:
        """Cluster membership changed: every route resolved under the old
        ring is invalid (scatter targets moved)."""
        emit = False
        with self._lock:
            if self._epoch != epoch:
                emit = self._epoch is not None and bool(self._entries)
                self._epoch = epoch
                if emit:
                    self._invalidations["epoch"] += 1
        if emit:
            self._inc_invalidation("epoch")
            self._emit_evict(None, "epoch")

    def clear(self) -> None:
        """Drop everything (tests / bench cold windows)."""
        with self._lock:
            self._entries.clear()
            self._warm.clear()
            self._by_stmt.clear()
            self._index_defs.clear()

    def reset_window(self) -> None:
        """Zero counters and timing but KEEP entries — the bench's warm
        measurement window starts here."""
        with self._lock:
            self._timing.clear()
            self._hits = {"ast": 0, "route": 0}
            self._misses.clear()
            self._invalidations.clear()
            self._verifies = {"ok": 0, "failed": 0}
            for e in self._entries.values():
                e.hits = e.misses = e.route_hits = 0

    # ------------------------------------------------------- timing
    def _note_timing(self, fp: str, phase: str, us: float, warm: bool) -> None:
        k = ("warm_" if warm else "cold_") + phase
        with self._lock:
            t = self._timing.get(fp)
            if t is None:
                t = self._timing[fp] = {}
                while len(self._timing) > self._cap * 2:
                    self._timing.popitem(last=False)
            t[k + "_us"] = t.get(k + "_us", 0.0) + us
            t[k + "_n"] = t.get(k + "_n", 0) + 1

    def note_plan_time(self, fp: Optional[str], us: float, warm: bool) -> None:
        """Pre-kernel plan/lower time attribution (planner + pipeline
        analyze); `fp` is the active statement fingerprint."""
        if fp and self.enabled:
            self._note_timing(fp, "plan", us, warm)

    # ------------------------------------------------------- views
    def _prekernel(self, t: Dict[str, float]) -> Dict[str, Any]:
        def avg(pfx: str) -> Optional[float]:
            n = t.get(pfx + "_parse_n", 0) + 0
            us = t.get(pfx + "_parse_us", 0.0)
            pn = t.get(pfx + "_plan_n", 0)
            pus = t.get(pfx + "_plan_us", 0.0)
            parse = us / n if n else None
            plan = pus / pn if pn else None
            if parse is None and plan is None:
                return None
            return round((parse or 0.0) + (plan or 0.0), 2)

        return {"cold_us": avg("cold"), "warm_us": avg("warm")}

    def window_stats(self, per_fp_limit: int = 20) -> dict:
        """The bench embed: window hit rates + per-fingerprint pre-kernel
        overhead, warm vs cold."""
        with self._lock:
            hits = dict(self._hits)
            misses = sum(self._misses.values())
            inv = dict(self._invalidations)
            verifies = dict(self._verifies)
            timing = {fp: dict(t) for fp, t in self._timing.items()}
            entries = len(self._entries)
            variants = sum(len(e.variants) for e in self._entries.values())
        total = hits["ast"] + misses
        fps = []
        for fp, t in timing.items():
            pk = self._prekernel(t)
            if pk["cold_us"] is None and pk["warm_us"] is None:
                continue
            fps.append({"fingerprint": fp, **pk})
        fps.sort(key=lambda r: (r["cold_us"] or 0.0), reverse=True)
        colds = [r["cold_us"] for r in fps if r["cold_us"] is not None]
        warms = [r["warm_us"] for r in fps if r["warm_us"] is not None]
        return {
            "enabled": self.enabled,
            "entries": entries,
            "variants": variants,
            "hits": hits["ast"],
            "route_hits": hits["route"],
            "misses": misses,
            "hit_rate": round(hits["ast"] / total, 4) if total else None,
            "invalidations": inv,
            "verifies": verifies,
            "prekernel": {
                "cold_avg_us": round(sum(colds) / len(colds), 2)
                if colds
                else None,
                "warm_avg_us": round(sum(warms) / len(warms), 2)
                if warms
                else None,
            },
            "fingerprints": fps[: max(per_fp_limit, 1)],
        }

    def snapshot(self, limit: int = 20) -> dict:
        """The debug bundle's `plan_cache` section."""
        with self._lock:
            rows = []
            for fp, e in list(self._entries.items())[-limit:]:
                rows.append(
                    {
                        "fingerprint": fp,
                        "sql": e.variants[0].text if e.variants else None,
                        "variants": len(e.variants),
                        "hits": e.hits,
                        "route_hits": e.route_hits,
                        "misses": e.misses,
                        "routes": sum(
                            len(v.routes) for v in e.variants
                        ),
                        "fronts": sorted(
                            {
                                r.front
                                for v in e.variants
                                for r in v.routes.values()
                            }
                        ),
                        "parameterized": any(
                            v.parameterized for v in e.variants
                        ),
                    }
                )
            state = {
                "enabled": self.enabled,
                "cap": self._cap,
                "min_hits": self._min_hits,
                "entries": len(self._entries),
                "hits": dict(self._hits),
                "misses": dict(self._misses),
                "invalidations": dict(self._invalidations),
                "verifies": dict(self._verifies),
                "epoch": self._epoch,
                "generations": {
                    f"{ns}/{db}": g for (ns, db), g in self._gen.items()
                },
                "recent_evictions": list(self._evlog)[-16:],
            }
        state["top"] = rows[::-1]
        return state

    def describe(self, fp: str) -> Optional[dict]:
        """One fingerprint's cache state — the /statements annotation."""
        with self._lock:
            e = self._entries.get(fp)
            if e is None:
                n = self._warm.get(fp)
                return {"cached": False, "observed": n} if n else None
            return {
                "cached": True,
                "variants": len(e.variants),
                "hits": e.hits,
                "route_hits": e.route_hits,
                "misses": e.misses,
                "fronts": sorted(
                    {
                        r.front
                        for v in e.variants
                        for r in v.routes.values()
                    }
                ),
            }

    def annotate(self, rows: List[dict]) -> List[dict]:
        """Attach `plan_cache` state to /statements rows in place."""
        for row in rows:
            fp = row.get("fingerprint")
            if fp and "plan_cache" not in row:
                got = self.describe(fp)
                if got is not None:
                    row["plan_cache"] = got
        return rows

    def review_rows(self, min_calls: int = 8) -> List[dict]:
        """The advisor's raw material: low-hit-rate entries and
        thrash-evicted fingerprints (evicted 2+ times recently)."""
        with self._lock:
            out = []
            for fp, e in self._entries.items():
                total = e.hits + e.misses
                if total >= min_calls and e.hits / total < 0.5:
                    out.append(
                        {
                            "fingerprint": fp,
                            "kind": "low_hit_rate",
                            "hits": e.hits,
                            "misses": e.misses,
                            "hit_rate": round(e.hits / total, 3),
                            "sql": e.variants[0].text
                            if e.variants
                            else None,
                        }
                    )
            thrash = Counter(
                ev["fp"] for ev in self._evlog if ev["fp"] is not None
            )
            for fp, n in thrash.items():
                if n >= 2:
                    out.append(
                        {
                            "fingerprint": fp,
                            "kind": "thrash",
                            "evictions": n,
                            "causes": sorted(
                                {
                                    ev["cause"]
                                    for ev in self._evlog
                                    if ev["fp"] == fp
                                }
                            ),
                        }
                    )
        return out

    # ------------------------------------------------------- emission
    # One helper per metric family so every emission site carries a STATIC
    # name and STATIC label keys (GL006: bounded series cardinality); the
    # variable part rides the label VALUE. All are called outside the
    # store lock (locks.HIERARCHY: telemetry and events are peers/lower
    # leaves — never nest under us).
    def _inc_hit(self, kind: str) -> None:
        from surrealdb_tpu import telemetry

        telemetry.inc("plan_cache_hits", kind=kind)

    def _inc_miss(self, cause: str) -> None:
        from surrealdb_tpu import telemetry

        telemetry.inc("plan_cache_misses", cause=cause)

    def _inc_invalidation(self, cause: str) -> None:
        from surrealdb_tpu import telemetry

        telemetry.inc("plan_cache_invalidations", cause=cause)

    def _emit_evict(self, fp: Optional[str], cause: str) -> None:
        from surrealdb_tpu import events

        events.emit("plan_cache.evict", fingerprint=fp, cause=cause)


# ------------------------------------------------------------------ registry
# every live PlanCache, so stats.record's flip hook (which has no ds
# handle) can reach them all — the same weak registry shape advisor uses
_caches: "weakref.WeakSet[PlanCache]" = weakref.WeakSet()


def on_plan_flip(fp: str) -> None:
    """stats.record's post-lock flip hook: evict `fp` everywhere."""
    for pc in list(_caches):
        pc.on_plan_flip(fp)


def active_plan_cache(ctx) -> Optional[PlanCache]:
    """The executing statement's datastore cache, or None (no executor on
    the context / cache disabled)."""
    ex = getattr(ctx, "executor", None)
    ds = getattr(ex, "ds", None)
    pc = getattr(ds, "plan_cache", None)
    if pc is not None and pc.enabled:
        return pc
    return None
