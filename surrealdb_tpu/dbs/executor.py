"""Statement loop and transaction management.

Role of the reference's Executor (reference: core/src/dbs/executor.rs:34-593):
runs each statement of a query, opening one transaction per bare statement or
one shared transaction for an explicit BEGIN..COMMIT block; buffers responses
inside an explicit transaction so a failure/cancel can retroactively flip
them; flushes live-query notifications only on successful commit.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Dict, List, Optional

from surrealdb_tpu import cnf
from surrealdb_tpu.err import (
    ControlFlow,
    QueryCancelledError,
    ReturnError,
    SurrealError,
)
from surrealdb_tpu.sql.statements import (
    AlterStatement,
    BeginStatement,
    CancelStatement,
    CommitStatement,
    DefineStatement,
    KillStatement,
    LiveStatement,
    OptionStatement,
    Query,
    RebuildStatement,
    RemoveStatement,
    UseStatement,
)
from surrealdb_tpu.sql.value import NONE, is_none

from .context import Context
from .session import Session

# Expression recursion is depth-limited by MAX_COMPUTATION_DEPTH (120), but
# each level can span many Python frames; mirror the reference's big-stack
# runtime setup (reference: src/main.rs:38-49 RUNTIME_STACK_SIZE).
if sys.getrecursionlimit() < 20_000:
    sys.setrecursionlimit(20_000)

_FAILED_TX = "The query was not executed due to a failed transaction"
_CANCELLED_TX = "The query was not executed due to a cancelled transaction"


def _fmt_time(seconds: float) -> str:
    if seconds < 1e-3:
        return f"{seconds * 1e6:.1f}µs"
    if seconds < 1.0:
        return f"{seconds * 1e3:.2f}ms"
    return f"{seconds:.3f}s"


class Executor:
    def __init__(self, ds, session: Session, vars: Optional[Dict[str, Any]] = None):
        self.ds = ds
        self.session = session
        self.vars = vars or {}
        self.txn = None
        self.explicit = False  # inside BEGIN..COMMIT
        self.failed: Optional[str] = None  # error text that poisoned the txn
        # plan-cache serve state (dbs/plan_cache.py): per-execution slot
        # bindings for a shared template AST (read by SlotLiteral.compute
        # through ctx.executor), whether this execution was served warm,
        # and the schema-generation token captured at statement start
        # (plan artifacts installed under a stale token are refused)
        self.slot_values: Optional[tuple] = None
        self.cache_warm = False
        self.plan_gen: Optional[tuple] = None
        self._ddl_open: List[tuple] = []  # DDL brackets held to COMMIT/CANCEL
        self._buffered: List[dict] = []  # responses inside the explicit txn
        self._notifications: List[Any] = []

    # ------------------------------------------------------------ txns
    def current_txn(self):
        return self.txn

    def _open(self, write: bool) -> None:
        if self.txn is None or self.txn.done:
            self.txn = self.ds.transaction(write)

    def _commit(self) -> None:
        if self.txn is not None and not self.txn.done:
            self.txn.commit()
            self._flush_notifications()
        self.txn = None
        self._close_ddl_brackets()

    def _cancel(self) -> None:
        if self.txn is not None and not self.txn.done:
            self.txn.cancel()
        self.txn = None
        self._notifications = []
        self._close_ddl_brackets()

    def _close_ddl_brackets(self) -> None:
        """Release plan-cache DDL brackets held across an explicit txn
        (the schema change is now committed or cancelled either way)."""
        if self._ddl_open:
            pc = self.ds.plan_cache
            for ns, db in self._ddl_open:
                pc.ddl_end(ns, db)
            self._ddl_open = []

    # ------------------------------------------------------------ notifications
    def buffer_notification(self, n) -> None:
        self._notifications.append(n)

    def _flush_notifications(self) -> None:
        hub = self.ds.notifications
        if hub is not None:
            for n in self._notifications:
                hub.publish(n)
        self._notifications = []

    # ------------------------------------------------------------ main loop
    def execute(self, query: Query) -> List[dict]:
        out: List[dict] = []
        ctx = Context(self, self.session)
        for name, value in self.vars.items():
            ctx.set_param(name, value)

        # per-statement source spans (syn/parser.py) feed the workload
        # statistics plane; reprs stand in for programmatic ASTs (a length
        # mismatch must never drop a statement from the zip)
        sources = query.sources
        if sources is None or len(sources) != len(query.statements):
            sources = [repr(s) for s in query.statements]
        for stm, src in zip(query.statements, sources):
            t0 = time.perf_counter()

            if isinstance(stm, BeginStatement):
                if not self.explicit:
                    self._open(True)
                    self.explicit = True
                    self.failed = None
                    self._buffered = []
                continue

            if isinstance(stm, CommitStatement):
                if self.explicit:
                    if self.failed is None:
                        try:
                            self._commit()
                        except SurrealError as e:
                            self.failed = str(e)
                            self._cancel()
                    else:
                        self._cancel()
                    if self.failed is not None:
                        for r in self._buffered:
                            if r["status"] == "OK":
                                r["status"] = "ERR"
                                r["result"] = _FAILED_TX
                    out.extend(self._buffered)
                    self._buffered = []
                    self.explicit = False
                    self.failed = None
                continue

            if isinstance(stm, CancelStatement):
                if self.explicit:
                    self._cancel()
                    for r in self._buffered:
                        r["status"] = "ERR"
                        r["result"] = _CANCELLED_TX
                    out.extend(self._buffered)
                    self._buffered = []
                    self.explicit = False
                    self.failed = None
                continue

            # inside a poisoned explicit transaction: report, don't run
            if self.explicit and self.failed is not None:
                self._push(out, {"status": "ERR", "result": _FAILED_TX, "time": _fmt_time(0)})
                continue

            resp = self._run_statement(ctx, stm, src)
            resp["time"] = _fmt_time(time.perf_counter() - t0)
            self._push(out, resp)

        # an unterminated BEGIN block: treat like CANCEL (reference cancels on drop)
        if self.explicit:
            self._cancel()
            for r in self._buffered:
                r["status"] = "ERR"
                r["result"] = _CANCELLED_TX
            out.extend(self._buffered)
            self._buffered = []
            self.explicit = False

        return out

    def _push(self, out: List[dict], resp: dict) -> None:
        if self.explicit:
            self._buffered.append(resp)
        else:
            out.append(resp)

    def _run_statement(self, ctx: Context, stm, src: Optional[str] = None) -> dict:
        # session-state statements need no transaction
        if isinstance(stm, (UseStatement, OptionStatement)):
            try:
                stm.compute(ctx)
                return {"status": "OK", "result": NONE}
            except SurrealError as e:
                return {"status": "ERR", "result": str(e)}

        from surrealdb_tpu import accounting, stats, telemetry, tracing

        # workload statistics plane: the literal-erased statement shape.
        # The fingerprint rides the trace meta (kept traces join their
        # stats row) and the per-thread activation table (the sampling
        # profiler attributes wall-clock samples to it).
        fp, norm = stats.fingerprint(src if src else repr(stm))
        tracing.annotate(**self._session_info(), fingerprint=fp)
        t0 = time.perf_counter()
        cpu0 = time.thread_time()
        dstats0 = self.ds.dispatch.stats()
        # rows_in: bulk-ingest rows landed over this statement's window
        # (process-global counter delta, like the dispatch delta below)
        bulk0 = telemetry.get_counter("bulk_insert_rows")
        telemetry.drain_plan_notes()  # clear notes left by a prior statement
        tok = stats.activate(fp)
        # tenant accounting: the statement executes FOR session (ns, db) —
        # the activation is what dispatch riders, bg registrations and the
        # profiler's cross-thread reads attribute through; the tally is
        # the iterator's rows-scanned scratch, flushed below
        atok = accounting.activate(self.session.ns, self.session.db)
        tally0 = accounting.tally_begin()
        # plan cache: capture the schema-generation token this statement
        # plans under; DDL brackets itself so artifacts raced against a
        # concurrent schema change can never install (dbs/plan_cache.py)
        pc = self.ds.plan_cache
        ddl = isinstance(
            stm,
            (DefineStatement, RemoveStatement, AlterStatement,
             RebuildStatement),
        )
        self.plan_gen = pc.gen_token(self.session.ns, self.session.db)
        if ddl:
            pc.ddl_begin(self.session.ns, self.session.db)
        try:
            resp = self._execute_statement(ctx, stm)
        finally:
            scanned = accounting.tally_end(tally0)
            accounting.deactivate(atok)
            stats.deactivate(tok)
            if ddl:
                if self.explicit:
                    # the schema change lands at COMMIT (or dies at
                    # CANCEL): hold the bracket open until then
                    self._ddl_open.append(
                        (self.session.ns, self.session.db)
                    )
                else:
                    pc.ddl_end(self.session.ns, self.session.db)
        dt = time.perf_counter() - t0
        cpu_s = time.thread_time() - cpu0
        # drained ONCE per statement: the stats record and the slow-query
        # ring read the same plan-note list
        notes = telemetry.drain_plan_notes()
        d1 = self.ds.dispatch.stats()
        dispatch_delta = {k: round(d1[k] - dstats0[k], 4) for k in d1}
        errored = resp.get("status") == "ERR"
        slow = dt >= cnf.SLOW_QUERY_THRESHOLD_SECS
        result = resp.get("result")
        rows_out = (
            len(result) if isinstance(result, list) else (0 if errored else 1)
        )
        rows_in = int(telemetry.get_counter("bulk_insert_rows") - bulk0)
        stats.record(
            fp, norm, type(stm).__name__, dt,
            error=errored, slow=slow, rows_out=rows_out,
            rows_in=rows_in,
            plan=notes, dispatch=dispatch_delta,
        )
        # tenant accounting flush: ONE charge per statement, mirrored into
        # the global conservation counters with the SAME values so
        # per-tenant sums reconcile against independent telemetry totals
        rows_scanned = scanned.get("rows_scanned", 0.0)
        telemetry.inc("statement_cpu_seconds", by=cpu_s)
        telemetry.inc("statement_rows_scanned", by=rows_scanned)
        telemetry.inc("statement_rows_returned", by=float(rows_out))
        accounting.charge(
            self.session.ns, self.session.db, fingerprint=fp,
            statements=1, errors=1 if errored else 0, slow=1 if slow else 0,
            exec_s=dt, cpu_s=cpu_s, rows_scanned=rows_scanned,
            rows_returned=rows_out, rows_written=rows_in,
        )
        if errored:
            telemetry.inc("statement_errors", kind=type(stm).__name__)
            # joinable side of the counter: cite the request's trace (and
            # pin it — the citation must stay resolvable via /trace/:id)
            tracing.force_keep()
            telemetry.record_error(
                {
                    "ts": time.time(),
                    "kind": type(stm).__name__,
                    "error": str(resp["result"])[:300],
                    "trace_id": tracing.current_trace_id(),
                    "fingerprint": fp,
                    "session": self._session_info(),
                }
            )
        if slow:
            # structured slow-query record (reference: query duration
            # warnings in telemetry/metrics) — ring-buffered with the plan
            # decisions plus the dispatch-queue delta over this statement's
            # window (process-global: concurrent statements' dispatches are
            # included), drained via telemetry.snapshot() or GET /slow
            kind = type(stm).__name__
            telemetry.inc("slow_queries", kind=kind)
            tracing.force_keep()  # /slow -> /trace/:id must be one hop
            telemetry.record_slow_query(
                {
                    "ts": time.time(),
                    "sql": repr(stm)[:500],
                    "kind": kind,
                    "duration_s": round(dt, 6),
                    "plan": notes,
                    "dispatch": dispatch_delta,
                    "trace_id": tracing.current_trace_id(),
                    "fingerprint": fp,
                    "session": self._session_info(),
                    "error": str(resp["result"])[:500]
                    if resp.get("status") == "ERR"
                    else None,
                }
            )
        return resp

    def _session_info(self) -> dict:
        """Joinable request context: ns/db and the auth LEVEL only — a
        token or credential must never reach a log surface."""
        s = self.session
        return {
            "ns": s.ns,
            "db": s.db,
            "auth": getattr(s.auth, "level", None) or "anon",
        }

    def _execute_statement(self, ctx: Context, stm) -> dict:
        from surrealdb_tpu import telemetry

        writeable = stm.writeable()
        own_txn = not self.explicit
        if own_txn:
            self._open(writeable)
        try:
            try:
                with telemetry.span("statement", kind=type(stm).__name__):
                    result = stm.compute(ctx)
            except ReturnError as r:
                result = r.value
            if own_txn:
                if writeable:
                    self._commit()
                else:
                    self._cancel()
            return {"status": "OK", "result": result}
        except ControlFlow as e:
            # BREAK/CONTINUE outside a loop etc.
            if own_txn:
                self._cancel()
            if self.explicit:
                self.failed = str(e)
            return {"status": "ERR", "result": f"Unexpected control flow: {e}"}
        except SurrealError as e:
            if own_txn:
                self._cancel()
            if self.explicit:
                self.failed = str(e)
            return {"status": "ERR", "result": str(e)}
        except Exception as e:
            # engine bugs must not leak transactions or abort the whole call
            if own_txn:
                self._cancel()
            if self.explicit:
                self.failed = str(e)
            return {"status": "ERR", "result": f"Internal error: {type(e).__name__}: {e}"}

    # ------------------------------------------------------------ expressions
    def compute_expression(self, expr) -> Any:
        """Evaluate one expression in its own transaction
        (reference kvs/ds.rs compute)."""
        ctx = Context(self, self.session)
        for name, value in self.vars.items():
            ctx.set_param(name, value)
        self._open(getattr(expr, "writeable", lambda: False)())
        try:
            try:
                v = expr.compute(ctx)
            except ReturnError as r:
                v = r.value
            self._commit()
            return v
        except BaseException:
            self._cancel()
            raise
