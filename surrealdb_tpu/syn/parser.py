"""SurrealQL recursive-descent parser.

Role of the reference's parser (reference: core/src/syn/parser/mod.rs:1-44 and
syn/parser/stmt/). Pratt-style expression parsing over the token stream from
lexer.py; keywords are case-insensitive and recognised contextually.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from surrealdb_tpu.err import ParseError
from surrealdb_tpu.sql import ast as A
from surrealdb_tpu.sql import statements as S
from surrealdb_tpu.sql.kind import Kind
from surrealdb_tpu.sql import path as P
from surrealdb_tpu.sql.value import (
    NONE,
    Datetime,
    Duration,
    Null,
    Range,
    Thing,
    Uuid,
)
from .lexer import Token, lex

# infix binding powers
_BP = {
    "||": (10, 11), "OR": (10, 11),
    "&&": (20, 21), "AND": (20, 21),
    "??": (30, 31), "?:": (30, 31),
    "=": (40, 41), "!=": (40, 41), "==": (40, 41), "?=": (40, 41), "*=": (40, 41),
    "~": (40, 41), "!~": (40, 41), "?~": (40, 41), "*~": (40, 41),
    "<": (40, 41), "<=": (40, 41), ">": (40, 41), ">=": (40, 41),
    "IN": (40, 41), "INSIDE": (40, 41), "NOTINSIDE": (40, 41),
    "CONTAINS": (40, 41), "CONTAINSNOT": (40, 41), "CONTAINSALL": (40, 41),
    "CONTAINSANY": (40, 41), "CONTAINSNONE": (40, 41),
    "ALLINSIDE": (40, 41), "ANYINSIDE": (40, 41), "NONEINSIDE": (40, 41),
    "OUTSIDE": (40, 41), "INTERSECTS": (40, 41), "IS": (40, 41),
    "∈": (40, 41), "∉": (40, 41), "∋": (40, 41), "∌": (40, 41),
    "⊇": (40, 41), "⊃": (40, 41), "⊅": (40, 41), "⊆": (40, 41), "⊂": (40, 41), "⊄": (40, 41),
    "..": (50, 51),
    "+": (60, 61), "-": (60, 61),
    "*": (70, 71), "/": (70, 71), "×": (70, 71), "÷": (70, 71), "%": (70, 71),
    "**": (81, 80),  # right-assoc
}

_STMT_KEYWORDS = {
    "USE", "LET", "RETURN", "IF", "FOR", "BREAK", "CONTINUE", "THROW",
    "SELECT", "CREATE", "INSERT", "UPDATE", "UPSERT", "DELETE", "RELATE",
    "DEFINE", "REMOVE", "ALTER", "REBUILD", "INFO", "BEGIN", "COMMIT",
    "CANCEL", "LIVE", "KILL", "SHOW", "SLEEP", "OPTION", "ACCESS",
}

_CAST_KINDS = {
    "bool", "int", "float", "string", "number", "decimal", "datetime",
    "duration", "uuid", "array", "set", "record", "geometry", "regex", "bytes",
}


# deep enough for any real query (compute bounds expressions at 120 anyway,
# cnf MAX_COMPUTATION_DEPTH); shallow enough that ~6 Python frames per level
# stay far from the C-stack limit the 20k recursionlimit cannot see
_MAX_PARSE_DEPTH = 500


class Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = lex(text)
        self.i = 0
        self._no_graph = 0  # >0: don't consume ->/<- as idiom parts (RELATE)
        self._depth = 0  # expression nesting, bounded by _MAX_PARSE_DEPTH

    # ------------------------------------------------------------- helpers
    def peek(self, off: int = 0) -> Token:
        j = min(self.i + off, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "EOF":
            self.i += 1
        return t

    def expect_int(self, what: str) -> int:
        """Next token as an integer, or a clean parse error."""
        tok = self.next()
        try:
            return int(tok.value)
        except (TypeError, ValueError, OverflowError):
            raise self.error(f"expected {what}", tok)

    def error(self, msg: str, tok: Optional[Token] = None) -> ParseError:
        t = tok or self.peek()
        line = self.text.count("\n", 0, t.pos) + 1
        col = t.pos - (self.text.rfind("\n", 0, t.pos) + 1) + 1
        return ParseError(msg, t.pos, line, col)

    def is_kw(self, word: str, off: int = 0) -> bool:
        t = self.peek(off)
        return t.kind == "IDENT" and t.value.upper() == word

    def eat_kw(self, word: str) -> bool:
        if self.is_kw(word):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.eat_kw(word):
            raise self.error(f"expected {word}")

    def is_op(self, op: str, off: int = 0) -> bool:
        t = self.peek(off)
        return t.kind == "OP" and t.value == op

    def eat_op(self, op: str) -> bool:
        if self.is_op(op):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            raise self.error(f"expected {op!r}")

    def ident(self, what: str = "identifier") -> str:
        t = self.peek()
        if t.kind == "IDENT":
            self.next()
            return t.value
        if t.kind == "NUMBER" and isinstance(t.value, int):
            self.next()
            return str(t.value)
        if t.kind == "STRING":
            self.next()
            return t.value
        raise self.error(f"expected {what}")

    # ------------------------------------------------------------- query
    def parse_query(self) -> S.Query:
        stmts: List[S.Statement] = []
        spans: List[tuple] = []
        while True:
            while self.eat_op(";"):
                pass
            if self.peek().kind == "EOF":
                break
            start = self.peek().pos
            stmts.append(self.parse_statement())
            spans.append((start, self.peek().pos))
            if self.peek().kind == "EOF":
                break
            if not self.eat_op(";"):
                raise self.error("expected ;")
        return S.Query(
            stmts, sources=[self.text[a:b].strip() for a, b in spans]
        )

    # ------------------------------------------------------------- statements
    def parse_statement(self) -> S.Statement:
        t = self.peek()
        if t.kind == "IDENT":
            kw = t.value.upper()
            m = getattr(self, f"_stmt_{kw.lower()}", None)
            if kw in _STMT_KEYWORDS and m is not None:
                return m()
        # bare expression statement
        expr = self.parse_expr()
        return _ExprStatement(expr)

    def _stmt_use(self) -> S.Statement:
        self.next()
        ns = db = None
        while True:
            if self.eat_kw("NS") or self.eat_kw("NAMESPACE"):
                ns = self.ident("namespace name")
            elif self.eat_kw("DB") or self.eat_kw("DATABASE"):
                db = self.ident("database name")
            else:
                break
        if ns is None and db is None:
            raise self.error("expected NS or DB after USE")
        return S.UseStatement(ns, db)

    def _stmt_let(self) -> S.Statement:
        self.next()
        t = self.next()
        if t.kind != "PARAM":
            raise self.error("expected $param after LET", t)
        kind = None
        if self.eat_op(":"):
            kind = self.parse_kind()
        self.expect_op("=")
        return S.LetStatement(t.value, self.parse_expr(), kind)

    def _stmt_return(self) -> S.Statement:
        self.next()
        what = self.parse_expr()
        fetch = None
        if self.eat_kw("FETCH"):
            fetch = self._idiom_list()
        return S.ReturnStatement(what, fetch)

    def _stmt_if(self) -> S.Statement:
        self.next()
        return self._parse_if_tail()

    def _parse_if_tail(self) -> S.IfStatement:
        branches = []
        cond = self.parse_expr()
        if self.eat_kw("THEN"):  # legacy syntax IF c THEN x ELSE y END
            then = self.parse_expr()
            branches.append((cond, then))
            while self.eat_kw("ELSE"):
                if self.eat_kw("IF"):
                    c2 = self.parse_expr()
                    self.expect_kw("THEN")
                    branches.append((c2, self.parse_expr()))
                else:
                    el = self.parse_expr()
                    self.eat_kw("END")
                    return S.IfStatement(branches, el)
            self.eat_kw("END")
            return S.IfStatement(branches, None)
        then = self.parse_block_expr()
        branches.append((cond, then))
        else_ = None
        while self.eat_kw("ELSE"):
            if self.eat_kw("IF"):
                c2 = self.parse_expr()
                branches.append((c2, self.parse_block_expr()))
            else:
                else_ = self.parse_block_expr()
                break
        return S.IfStatement(branches, else_)

    def _stmt_for(self) -> S.Statement:
        self.next()
        t = self.next()
        if t.kind != "PARAM":
            raise self.error("expected $param after FOR", t)
        self.expect_kw("IN")
        what = self.parse_expr()
        block = self.parse_block_expr()
        return S.ForStatement(t.value, what, block)

    def _stmt_break(self) -> S.Statement:
        self.next()
        return S.BreakStatement()

    def _stmt_continue(self) -> S.Statement:
        self.next()
        return S.ContinueStatement()

    def _stmt_throw(self) -> S.Statement:
        self.next()
        return S.ThrowStatement(self.parse_expr())

    def _stmt_begin(self) -> S.Statement:
        self.next()
        self.eat_kw("TRANSACTION")
        return S.BeginStatement()

    def _stmt_commit(self) -> S.Statement:
        self.next()
        self.eat_kw("TRANSACTION")
        return S.CommitStatement()

    def _stmt_cancel(self) -> S.Statement:
        self.next()
        self.eat_kw("TRANSACTION")
        return S.CancelStatement()

    def _stmt_sleep(self) -> S.Statement:
        self.next()
        t = self.next()
        if t.kind != "DURATION":
            raise self.error("expected duration after SLEEP", t)
        return S.SleepStatement(t.value)

    def _stmt_option(self) -> S.Statement:
        self.next()
        name = self.ident("option name")
        val = True
        if self.eat_op("="):
            if self.eat_kw("TRUE"):
                val = True
            elif self.eat_kw("FALSE"):
                val = False
            else:
                raise self.error("expected true or false")
        return S.OptionStatement(name.upper(), val)

    def _stmt_info(self) -> S.Statement:
        self.next()
        self.expect_kw("FOR")
        if self.eat_kw("ROOT") or self.eat_kw("KV"):
            lvl, target = "root", None
        elif self.eat_kw("NS") or self.eat_kw("NAMESPACE"):
            lvl, target = "ns", None
        elif self.eat_kw("DB") or self.eat_kw("DATABASE"):
            lvl, target = "db", None
        elif self.eat_kw("TABLE"):
            lvl, target = "table", self.ident("table name")
        elif self.eat_kw("INDEX"):
            name = self.ident("index name")
            self.expect_kw("ON")
            self.eat_kw("TABLE")
            tb = self.ident("table name")
            return S.InfoStatement("index", f"{name}:{tb}")
        elif self.eat_kw("USER"):
            lvl, target = "user", self.ident("user name")
        else:
            raise self.error("expected ROOT, NS, DB, TABLE, INDEX or USER")
        structure = self.eat_kw("STRUCTURE")
        return S.InfoStatement(lvl, target, structure)

    # ---------------------------------------------------------- SELECT
    def _stmt_select(self) -> S.Statement:
        self.next()
        value_mode = False
        fields: List[S.Field] = []
        if self.eat_kw("VALUE"):
            value_mode = True
            expr = self.parse_expr()
            alias = None
            if self.eat_kw("AS"):
                alias = self.parse_plain_idiom()
            fields.append(S.Field(expr, alias))
        else:
            while True:
                if self.is_op("*"):
                    self.next()
                    fields.append(S.Field(None, all_=True))
                else:
                    expr = self.parse_expr()
                    alias = None
                    if self.eat_kw("AS"):
                        alias = self.parse_plain_idiom()
                    fields.append(S.Field(expr, alias))
                if not self.eat_op(","):
                    break
        omit = None
        if self.eat_kw("OMIT"):
            omit = self._idiom_list()
        self.expect_kw("FROM")
        only = self.eat_kw("ONLY")
        what = [self.parse_expr()]
        while self.eat_op(","):
            what.append(self.parse_expr())
        kw: dict = {"omit": omit, "only": only, "value_mode": value_mode}
        if self.eat_kw("WITH"):
            if self.eat_kw("NOINDEX"):
                kw["with_"] = S.With(True)
            else:
                self.expect_kw("INDEX")
                names = [self.ident("index name")]
                while self.eat_op(","):
                    names.append(self.ident("index name"))
                kw["with_"] = S.With(False, names)
        if self.eat_kw("WHERE"):
            kw["cond"] = self.parse_expr()
        if self.eat_kw("SPLIT"):
            self.eat_kw("ON")
            kw["split"] = self._idiom_list()
        if self.eat_kw("GROUP"):
            if self.eat_kw("ALL"):
                kw["group_all"] = True
            else:
                self.eat_kw("BY")
                kw["group"] = self._idiom_list()
        if self.eat_kw("ORDER"):
            self.eat_kw("BY")
            orders = []
            while True:
                if self.is_kw("RAND") and self.peek(1).kind == "OP" and self.peek(1).value == "(":
                    self.next(); self.next(); self.expect_op(")")
                    orders.append(S.OrderItem(None, rand=True))
                else:
                    idm = self.parse_plain_idiom()
                    collate = self.eat_kw("COLLATE")
                    numeric = self.eat_kw("NUMERIC")
                    asc = True
                    if self.eat_kw("DESC"):
                        asc = False
                    else:
                        self.eat_kw("ASC")
                    orders.append(S.OrderItem(idm, asc, collate, numeric))
                if not self.eat_op(","):
                    break
            kw["order"] = orders
        if self.eat_kw("LIMIT"):
            self.eat_kw("BY")
            kw["limit"] = self.parse_expr()
        if self.eat_kw("START"):
            self.eat_kw("AT")
            kw["start"] = self.parse_expr()
        if self.eat_kw("FETCH"):
            kw["fetch"] = self._idiom_list()
        if self.eat_kw("VERSION"):
            kw["version"] = self.parse_expr()
        if self.eat_kw("TIMEOUT"):
            kw["timeout"] = self._duration()
        if self.eat_kw("PARALLEL"):
            kw["parallel"] = True
        if self.eat_kw("TEMPFILES"):
            kw["tempfiles"] = True
        if self.eat_kw("EXPLAIN"):
            kw["explain"] = True
            kw["explain_full"] = self.eat_kw("FULL")
            # EXPLAIN ANALYZE: run the statement for real and report the
            # plan WITH execution statistics (per-shard profile in cluster
            # mode) instead of the plan alone
            kw["explain_analyze"] = self.eat_kw("ANALYZE")
        kw.pop("tempfiles", None)
        return S.SelectStatement(fields, what, **kw)

    def _idiom_list(self) -> List[P.Idiom]:
        out = [self.parse_plain_idiom()]
        while self.eat_op(","):
            out.append(self.parse_plain_idiom())
        return out

    def _duration(self) -> Duration:
        t = self.next()
        if t.kind != "DURATION":
            raise self.error("expected duration", t)
        return t.value

    # ---------------------------------------------------------- CRUD
    def _data_clause(self) -> Optional[S.Data]:
        if self.eat_kw("SET"):
            items = []
            while True:
                idm = self.parse_plain_idiom()
                t = self.next()
                if t.kind != "OP" or t.value not in ("=", "+=", "-=", "+?="):
                    raise self.error("expected assignment operator", t)
                items.append((idm, t.value, self.parse_expr()))
                if not self.eat_op(","):
                    break
            return S.Data("set", items)
        if self.eat_kw("UNSET"):
            return S.Data("unset", self._idiom_list())
        if self.eat_kw("CONTENT"):
            return S.Data("content", self.parse_expr())
        if self.eat_kw("MERGE"):
            return S.Data("merge", self.parse_expr())
        if self.eat_kw("PATCH"):
            return S.Data("patch", self.parse_expr())
        if self.eat_kw("REPLACE"):
            return S.Data("replace", self.parse_expr())
        return None

    def _output_clause(self) -> Optional[S.Output]:
        if not self.eat_kw("RETURN"):
            return None
        if self.eat_kw("NONE"):
            return S.Output("none")
        if self.eat_kw("NULL"):
            return S.Output("null")
        if self.eat_kw("DIFF"):
            return S.Output("diff")
        if self.eat_kw("BEFORE"):
            return S.Output("before")
        if self.eat_kw("AFTER"):
            return S.Output("after")
        if self.eat_kw("VALUE"):
            expr = self.parse_expr()
            return S.Output("fields", [S.Field(expr, None)])
        fields = []
        while True:
            expr = self.parse_expr()
            alias = None
            if self.eat_kw("AS"):
                alias = self.parse_plain_idiom()
            fields.append(S.Field(expr, alias))
            if not self.eat_op(","):
                break
        return S.Output("fields", fields)

    def _common_tail(self, kw: dict) -> None:
        if self.eat_kw("TIMEOUT"):
            kw["timeout"] = self._duration()
        if self.eat_kw("PARALLEL"):
            kw["parallel"] = True

    def _stmt_create(self) -> S.Statement:
        self.next()
        only = self.eat_kw("ONLY")
        what = [self.parse_expr()]
        while self.eat_op(","):
            what.append(self.parse_expr())
        kw: dict = {"only": only}
        kw["data"] = self._data_clause()
        kw["output"] = self._output_clause()
        if self.eat_kw("VERSION"):
            kw["version"] = self.parse_expr()
        self._common_tail(kw)
        return S.CreateStatement(what, **kw)

    def _stmt_update(self) -> S.Statement:
        return self._update_like(S.UpdateStatement)

    def _stmt_upsert(self) -> S.Statement:
        return self._update_like(S.UpsertStatement)

    def _update_like(self, cls) -> S.Statement:
        self.next()
        only = self.eat_kw("ONLY")
        what = [self.parse_expr()]
        while self.eat_op(","):
            what.append(self.parse_expr())
        kw: dict = {"only": only}
        kw["data"] = self._data_clause()
        if self.eat_kw("WHERE"):
            kw["cond"] = self.parse_expr()
        kw["output"] = self._output_clause()
        self._common_tail(kw)
        return cls(what, **kw)

    def _stmt_delete(self) -> S.Statement:
        self.next()
        self.eat_kw("FROM")
        only = self.eat_kw("ONLY")
        what = [self.parse_expr()]
        while self.eat_op(","):
            what.append(self.parse_expr())
        kw: dict = {"only": only}
        if self.eat_kw("WHERE"):
            kw["cond"] = self.parse_expr()
        kw["output"] = self._output_clause()
        self._common_tail(kw)
        return S.DeleteStatement(what, **kw)

    def _stmt_insert(self) -> S.Statement:
        self.next()
        # accept RELATION/IGNORE in either order
        relation = self.eat_kw("RELATION")
        ignore = self.eat_kw("IGNORE")
        if not relation:
            relation = self.eat_kw("RELATION")
        into = None
        if self.eat_kw("INTO"):
            # a bare table name even when '(' follows (column-list form)
            t = self.peek()
            if t.kind == "IDENT" and not (
                self.peek(1).kind == "OP" and self.peek(1).value in ("::", ":")
            ):
                self.next()
                into = A.TableExpr(t.value)
            else:
                into = self.parse_expr()
        if self.is_op("("):
            # INSERT INTO tb (a, b) VALUES (1, 2), (3, 4)
            self.next()
            cols = [self.parse_plain_idiom()]
            while self.eat_op(","):
                cols.append(self.parse_plain_idiom())
            self.expect_op(")")
            self.expect_kw("VALUES")
            rows = []
            while True:
                self.expect_op("(")
                row = [self.parse_expr()]
                while self.eat_op(","):
                    row.append(self.parse_expr())
                self.expect_op(")")
                rows.append(row)
                if not self.eat_op(","):
                    break
            data = S.Data("values", (cols, rows))
        else:
            data = S.Data("content", self.parse_expr())
        kw: dict = {"ignore": ignore, "relation": relation}
        if self.eat_kw("ON"):
            self.expect_kw("DUPLICATE")
            self.expect_kw("KEY")
            self.expect_kw("UPDATE")
            items = []
            while True:
                idm = self.parse_plain_idiom()
                t = self.next()
                if t.kind != "OP" or t.value not in ("=", "+=", "-=", "+?="):
                    raise self.error("expected assignment operator", t)
                items.append((idm, t.value, self.parse_expr()))
                if not self.eat_op(","):
                    break
            kw["update"] = items
        kw["output"] = self._output_clause()
        if self.eat_kw("VERSION"):
            kw["version"] = self.parse_expr()
        self._common_tail(kw)
        return S.InsertStatement(into, data, **kw)

    def _relate_operand(self) -> A.Expr:
        self._no_graph += 1
        try:
            return self.parse_expr()
        finally:
            self._no_graph -= 1

    def _stmt_relate(self) -> S.Statement:
        self.next()
        only = self.eat_kw("ONLY")
        first = self._relate_operand()
        # RELATE from->edge->to  or  RELATE from, edge, to? (only arrow form)
        if self.is_op("->"):
            self.next()
            kind = self._relate_operand()
            self.expect_op("->")
            with_ = self._relate_operand()
            from_ = first
        elif self.is_op("<-"):
            self.next()
            kind = self._relate_operand()
            self.expect_op("<-")
            from_ = self._relate_operand()
            with_ = first
        else:
            raise self.error("expected -> or <- in RELATE")
        kw: dict = {"only": only}
        kw["uniq"] = self.eat_kw("UNIQUE")
        kw["data"] = self._data_clause()
        kw["output"] = self._output_clause()
        self._common_tail(kw)
        return S.RelateStatement(kind, from_, with_, **kw)

    # ---------------------------------------------------------- LIVE
    def _stmt_live(self) -> S.Statement:
        self.next()
        self.expect_kw("SELECT")
        diff = False
        fields: List[S.Field] = []
        if self.eat_kw("DIFF"):
            diff = True
        elif self.eat_kw("VALUE"):
            expr = self.parse_expr()
            fields.append(S.Field(expr, None))
        else:
            while True:
                if self.is_op("*"):
                    self.next()
                    fields.append(S.Field(None, all_=True))
                else:
                    expr = self.parse_expr()
                    alias = None
                    if self.eat_kw("AS"):
                        alias = self.parse_plain_idiom()
                    fields.append(S.Field(expr, alias))
                if not self.eat_op(","):
                    break
        self.expect_kw("FROM")
        what = self.parse_expr()
        cond = None
        if self.eat_kw("WHERE"):
            cond = self.parse_expr()
        fetch = None
        if self.eat_kw("FETCH"):
            fetch = self._idiom_list()
        return S.LiveStatement(fields, what, cond, fetch, diff)

    def _stmt_kill(self) -> S.Statement:
        self.next()
        return S.KillStatement(self.parse_expr())

    def _stmt_show(self) -> S.Statement:
        self.next()
        self.expect_kw("CHANGES")
        self.expect_kw("FOR")
        if self.eat_kw("DATABASE"):
            table = None
        else:
            self.expect_kw("TABLE")
            table = self.ident("table name")
        since = None
        if self.eat_kw("SINCE"):
            since = self.parse_expr()
        limit = None
        if self.eat_kw("LIMIT"):
            t = self.next()
            limit = t.value
        return S.ShowStatement(table, since, limit)

    # ---------------------------------------------------------- DEFINE
    def _if_not_exists(self) -> Tuple[bool, bool]:
        """-> (if_not_exists, overwrite)"""
        if self.eat_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True, False
        if self.eat_kw("OVERWRITE"):
            return False, True
        return False, False

    def _permissions_clause(self):
        """PERMISSIONS NONE|FULL|FOR select,create WHERE ..."""
        if not self.eat_kw("PERMISSIONS"):
            return None
        if self.eat_kw("NONE"):
            return {"select": "NONE", "create": "NONE", "update": "NONE", "delete": "NONE"}
        if self.eat_kw("FULL"):
            return {"select": "FULL", "create": "FULL", "update": "FULL", "delete": "FULL"}
        perms = {"select": "FULL", "create": "FULL", "update": "FULL", "delete": "FULL"}
        while self.is_kw("FOR"):
            self.next()
            kinds = []
            while True:
                k = self.ident("permission kind").lower()
                if k not in ("select", "create", "update", "delete"):
                    raise self.error(f"invalid permission kind {k}")
                kinds.append(k)
                if not self.eat_op(","):
                    break
            if self.eat_kw("NONE"):
                val: Any = "NONE"
            elif self.eat_kw("FULL"):
                val = "FULL"
            elif self.eat_kw("WHERE"):
                val = self.parse_expr()
            else:
                raise self.error("expected NONE, FULL or WHERE")
            for k in kinds:
                perms[k] = val
        return perms

    def _comment_clause(self) -> Optional[str]:
        if self.eat_kw("COMMENT"):
            t = self.next()
            return t.value if t.kind == "STRING" else str(t.value)
        return None

    def _stmt_define(self) -> S.Statement:
        self.next()
        if self.eat_kw("NAMESPACE") or self.eat_kw("NS"):
            ine, ow = self._if_not_exists()
            name = self.ident("namespace name")
            comment = self._comment_clause()
            return S.DefineStatement(
                "namespace", name=name, if_not_exists=ine, overwrite=ow, comment=comment
            )
        if self.eat_kw("DATABASE") or self.eat_kw("DB"):
            ine, ow = self._if_not_exists()
            name = self.ident("database name")
            changefeed = None
            comment = None
            while True:
                if self.eat_kw("CHANGEFEED"):
                    changefeed = {"expiry": self._duration().nanos, "original": False}
                    if self.eat_kw("INCLUDE"):
                        self.expect_kw("ORIGINAL")
                        changefeed["original"] = True
                elif self.is_kw("COMMENT"):
                    comment = self._comment_clause()
                else:
                    break
            return S.DefineStatement(
                "database", name=name, if_not_exists=ine, overwrite=ow,
                changefeed=changefeed, comment=comment,
            )
        if self.eat_kw("TABLE"):
            return self._define_table()
        if self.eat_kw("FIELD"):
            return self._define_field()
        if self.eat_kw("INDEX"):
            return self._define_index()
        if self.eat_kw("EVENT"):
            return self._define_event()
        if self.eat_kw("ANALYZER"):
            return self._define_analyzer()
        if self.eat_kw("FUNCTION"):
            return self._define_function()
        if self.eat_kw("PARAM"):
            ine, ow = self._if_not_exists()
            t = self.next()
            if t.kind != "PARAM":
                raise self.error("expected $param", t)
            self.expect_kw("VALUE")
            value = self.parse_expr()
            perms = self._permissions_clause()
            comment = self._comment_clause()
            return S.DefineStatement(
                "param", name=t.value, if_not_exists=ine, overwrite=ow,
                value=value, permissions=perms, comment=comment,
            )
        if self.eat_kw("USER"):
            return self._define_user()
        if self.eat_kw("ACCESS"):
            return self._define_access()
        if self.eat_kw("MODEL"):
            return self._define_model()
        if self.eat_kw("CONFIG"):
            kind = self.ident("config kind")
            rest_start = self.i
            depth = 0
            while self.peek().kind != "EOF" and not (self.is_op(";") and depth == 0):
                if self.peek().kind == "OP" and self.peek().value in "([{":
                    depth += 1
                if self.peek().kind == "OP" and self.peek().value in ")]}":
                    depth -= 1
                self.next()
            return S.DefineStatement("config", name=kind, raw=None)
        raise self.error("unknown DEFINE kind")

    def _define_table(self) -> S.Statement:
        ine, ow = self._if_not_exists()
        name = self.ident("table name")
        args: dict = {
            "name": name, "if_not_exists": ine, "overwrite": ow,
            "drop": False, "schemafull": False, "kind": "ANY",
            "relation_in": None, "relation_out": None, "enforced": False,
            "view": None, "changefeed": None, "permissions": None, "comment": None,
        }
        while True:
            if self.eat_kw("DROP"):
                args["drop"] = True
            elif self.eat_kw("SCHEMAFULL"):
                args["schemafull"] = True
            elif self.eat_kw("SCHEMALESS"):
                args["schemafull"] = False
            elif self.eat_kw("TYPE"):
                if self.eat_kw("ANY"):
                    args["kind"] = "ANY"
                elif self.eat_kw("NORMAL"):
                    args["kind"] = "NORMAL"
                elif self.eat_kw("RELATION"):
                    args["kind"] = "RELATION"
                    while True:
                        if self.eat_kw("IN") or self.eat_kw("FROM"):
                            tbs = [self.ident("table name")]
                            while self.eat_op("|"):
                                tbs.append(self.ident("table name"))
                            args["relation_in"] = tbs
                        elif self.eat_kw("OUT") or self.eat_kw("TO"):
                            tbs = [self.ident("table name")]
                            while self.eat_op("|"):
                                tbs.append(self.ident("table name"))
                            args["relation_out"] = tbs
                        elif self.eat_kw("ENFORCED"):
                            args["enforced"] = True
                        else:
                            break
                else:
                    raise self.error("expected ANY, NORMAL or RELATION")
            elif self.eat_kw("AS"):
                self.eat_op("(")
                sel = self._stmt_select_kw()
                self.eat_op(")")
                args["view"] = sel
            elif self.eat_kw("CHANGEFEED"):
                cf = {"expiry": self._duration().nanos, "original": False}
                if self.eat_kw("INCLUDE"):
                    self.expect_kw("ORIGINAL")
                    cf["original"] = True
                args["changefeed"] = cf
            elif self.is_kw("PERMISSIONS"):
                args["permissions"] = self._permissions_clause()
            elif self.is_kw("COMMENT"):
                args["comment"] = self._comment_clause()
            else:
                break
        return S.DefineStatement("table", **args)

    def _stmt_select_kw(self) -> S.SelectStatement:
        if not self.is_kw("SELECT"):
            raise self.error("expected SELECT")
        st = self._stmt_select()
        return st

    def _define_field(self) -> S.Statement:
        ine, ow = self._if_not_exists()
        name = self.parse_plain_idiom()
        self.expect_kw("ON")
        self.eat_kw("TABLE")
        tb = self.ident("table name")
        args: dict = {
            "name": name, "table": tb, "if_not_exists": ine, "overwrite": ow,
            "flex": False, "kind": None, "readonly": False, "value": None,
            "assert": None, "default": None, "default_always": False,
            "permissions": None, "comment": None, "reference": None,
        }
        while True:
            if self.eat_kw("FLEXIBLE") or self.eat_kw("FLEXI") or self.eat_kw("FLEX"):
                args["flex"] = True
            elif self.eat_kw("TYPE"):
                args["kind"] = self.parse_kind()
            elif self.eat_kw("READONLY"):
                args["readonly"] = True
            elif self.eat_kw("VALUE"):
                args["value"] = self.parse_expr()
            elif self.eat_kw("ASSERT"):
                args["assert"] = self.parse_expr()
            elif self.eat_kw("DEFAULT"):
                if self.eat_kw("ALWAYS"):
                    args["default_always"] = True
                args["default"] = self.parse_expr()
            elif self.is_kw("PERMISSIONS"):
                args["permissions"] = self._permissions_clause()
            elif self.is_kw("COMMENT"):
                args["comment"] = self._comment_clause()
            else:
                break
        return S.DefineStatement("field", **args)

    def _define_index(self) -> S.Statement:
        ine, ow = self._if_not_exists()
        name = self.ident("index name")
        self.expect_kw("ON")
        self.eat_kw("TABLE")
        tb = self.ident("table name")
        args: dict = {
            "name": name, "table": tb, "if_not_exists": ine, "overwrite": ow,
            "fields": [], "index": {"type": "idx"}, "comment": None,
            "concurrently": False,
        }
        if self.eat_kw("FIELDS") or self.eat_kw("COLUMNS"):
            args["fields"] = self._idiom_list()
        while True:
            if self.eat_kw("UNIQUE"):
                args["index"] = {"type": "uniq"}
            elif self.eat_kw("SEARCH"):
                ix = {"type": "search", "analyzer": "like", "k1": 1.2, "b": 0.75,
                      "highlights": False}
                if self.eat_kw("ANALYZER"):
                    ix["analyzer"] = self.ident("analyzer name")
                while True:
                    if self.eat_kw("BM25"):
                        # accepts both `BM25 1.2 0.75` and `BM25(1.2,0.75)`
                        parens = self.eat_op("(")
                        if self.peek().kind == "NUMBER":
                            ix["k1"] = float(self.next().value)
                            self.eat_op(",")
                            if self.peek().kind == "NUMBER":
                                ix["b"] = float(self.next().value)
                        if parens:
                            self.expect_op(")")
                    elif self.eat_kw("HIGHLIGHTS"):
                        ix["highlights"] = True
                    elif self.eat_kw("DOC_IDS_ORDER") or self.eat_kw("DOC_LENGTHS_ORDER") or self.eat_kw("POSTINGS_ORDER") or self.eat_kw("TERMS_ORDER"):
                        self.next()  # legacy btree orders; accepted, ignored
                    elif self.eat_kw("DOC_IDS_CACHE") or self.eat_kw("DOC_LENGTHS_CACHE") or self.eat_kw("POSTINGS_CACHE") or self.eat_kw("TERMS_CACHE"):
                        self.next()
                    else:
                        break
                args["index"] = ix
            elif self.eat_kw("MTREE"):
                ix = {"type": "mtree", "dimension": 0, "dist": "euclidean",
                      "vtype": "f64", "capacity": 40}
                while True:
                    if self.eat_kw("DIMENSION"):
                        ix["dimension"] = self.expect_int("a dimension")
                    elif self.eat_kw("DIST"):
                        ix["dist"] = self._distance_name()
                    elif self.eat_kw("TYPE"):
                        ix["vtype"] = self.ident("vector type").lower()
                    elif self.eat_kw("CAPACITY"):
                        ix["capacity"] = self.expect_int("a capacity")
                    else:
                        break
                args["index"] = ix
            elif self.eat_kw("HNSW"):
                ix = {"type": "hnsw", "dimension": 0, "dist": "euclidean",
                      "vtype": "f64", "efc": 150, "m": 12, "m0": 24, "lm": None}
                while True:
                    if self.eat_kw("DIMENSION"):
                        ix["dimension"] = self.expect_int("a dimension")
                    elif self.eat_kw("DIST"):
                        ix["dist"] = self._distance_name()
                    elif self.eat_kw("TYPE"):
                        ix["vtype"] = self.ident("vector type").lower()
                    elif self.eat_kw("EFC"):
                        ix["efc"] = self.expect_int("an EFC value")
                    elif self.eat_kw("M0"):
                        ix["m0"] = self.expect_int("an M0 value")
                    elif self.eat_kw("M"):
                        ix["m"] = self.expect_int("an M value")
                    elif self.eat_kw("LM"):
                        tok = self.next()
                        try:
                            ix["lm"] = float(tok.value)
                        except (TypeError, ValueError):
                            raise self.error("expected an LM value", tok)
                    elif self.eat_kw("EXTEND_CANDIDATES") or self.eat_kw("KEEP_PRUNED_CONNECTIONS"):
                        pass
                    else:
                        break
                if ix["lm"] is None:
                    import math as _m

                    ix["lm"] = 1.0 / _m.log(max(ix["m"], 2))
                args["index"] = ix
            elif self.eat_kw("CONCURRENTLY"):
                args["concurrently"] = True
            elif self.is_kw("COMMENT"):
                args["comment"] = self._comment_clause()
            else:
                break
        return S.DefineStatement("index", **args)

    def _distance_name(self) -> str:
        name = self.ident("distance").lower()
        if name == "minkowski":
            order = self.next()
            return f"minkowski:{order.value}"
        return name

    def _define_event(self) -> S.Statement:
        ine, ow = self._if_not_exists()
        name = self.ident("event name")
        self.expect_kw("ON")
        self.eat_kw("TABLE")
        tb = self.ident("table name")
        when = None
        if self.eat_kw("WHEN"):
            when = self.parse_expr()
        self.expect_kw("THEN")
        then = [self.parse_expr()]
        while self.eat_op(","):
            then.append(self.parse_expr())
        comment = self._comment_clause()
        return S.DefineStatement(
            "event", name=name, table=tb, if_not_exists=ine, overwrite=ow,
            when=when, then=then, comment=comment,
        )

    def _define_analyzer(self) -> S.Statement:
        ine, ow = self._if_not_exists()
        name = self.ident("analyzer name")
        tokenizers: List[str] = []
        filters: List[dict] = []
        function = None
        comment = None
        while True:
            if self.eat_kw("TOKENIZERS"):
                while True:
                    tokenizers.append(self.ident("tokenizer").lower())
                    if not self.eat_op(","):
                        break
            elif self.eat_kw("FILTERS"):
                while True:
                    fname = self.ident("filter").lower()
                    fargs = []
                    if self.eat_op("("):
                        while not self.is_op(")"):
                            t = self.next()
                            fargs.append(t.value)
                            self.eat_op(",")
                        self.expect_op(")")
                    filters.append({"name": fname, "args": fargs})
                    if not self.eat_op(","):
                        break
            elif self.eat_kw("FUNCTION"):
                self.eat_kw("FN")
                self.eat_op("::")
                function = self.ident("function name")
                while self.eat_op("::"):
                    function += "::" + self.ident("function name")
            elif self.is_kw("COMMENT"):
                comment = self._comment_clause()
            else:
                break
        return S.DefineStatement(
            "analyzer", name=name, if_not_exists=ine, overwrite=ow,
            tokenizers=tokenizers, filters=filters, function=function,
            comment=comment,
        )

    def _define_function(self) -> S.Statement:
        ine, ow = self._if_not_exists()
        self.expect_kw("FN")
        self.expect_op("::")
        name = self.ident("function name")
        while self.eat_op("::"):
            name += "::" + self.ident("function name")
        self.expect_op("(")
        params: List[Tuple[str, Optional[Kind]]] = []
        while not self.is_op(")"):
            t = self.next()
            if t.kind != "PARAM":
                raise self.error("expected $param", t)
            self.expect_op(":")
            kind = self.parse_kind()
            params.append((t.value, kind))
            if not self.eat_op(","):
                break
        self.expect_op(")")
        body = self.parse_block_expr()
        returns = None
        perms = None
        comment = None
        while True:
            if self.is_kw("PERMISSIONS"):
                if self.eat_kw("PERMISSIONS"):
                    if self.eat_kw("NONE"):
                        perms = "NONE"
                    elif self.eat_kw("FULL"):
                        perms = "FULL"
                    elif self.eat_kw("WHERE"):
                        perms = self.parse_expr()
            elif self.is_kw("COMMENT"):
                comment = self._comment_clause()
            else:
                break
        return S.DefineStatement(
            "function", name=name, if_not_exists=ine, overwrite=ow,
            params=params, body=body, returns=returns, permissions=perms,
            comment=comment,
        )

    def _define_user(self) -> S.Statement:
        ine, ow = self._if_not_exists()
        name = self.ident("user name")
        self.expect_kw("ON")
        if self.eat_kw("ROOT"):
            base = "root"
        elif self.eat_kw("NAMESPACE") or self.eat_kw("NS"):
            base = "ns"
        elif self.eat_kw("DATABASE") or self.eat_kw("DB"):
            base = "db"
        else:
            raise self.error("expected ROOT, NAMESPACE or DATABASE")
        password = passhash = None
        roles = ["Viewer"]
        token_dur = None
        session_dur = None
        comment = None
        while True:
            if self.eat_kw("PASSWORD"):
                password = self.next().value
            elif self.eat_kw("PASSHASH"):
                passhash = self.next().value
            elif self.eat_kw("ROLES"):
                roles = []
                while True:
                    roles.append(self.ident("role").capitalize())
                    if not self.eat_op(","):
                        break
            elif self.eat_kw("DURATION"):
                while self.eat_kw("FOR"):
                    if self.eat_kw("TOKEN"):
                        token_dur = self._duration().nanos
                    elif self.eat_kw("SESSION"):
                        if self.eat_kw("NONE"):
                            session_dur = None
                        else:
                            session_dur = self._duration().nanos
                    self.eat_op(",")
            elif self.is_kw("COMMENT"):
                comment = self._comment_clause()
            else:
                break
        return S.DefineStatement(
            "user", name=name, base=base, if_not_exists=ine, overwrite=ow,
            password=password, passhash=passhash, roles=roles,
            token_duration=token_dur, session_duration=session_dur,
            comment=comment,
        )

    def _define_access(self) -> S.Statement:
        ine, ow = self._if_not_exists()
        name = self.ident("access name")
        self.expect_kw("ON")
        if self.eat_kw("ROOT"):
            base = "root"
        elif self.eat_kw("NAMESPACE") or self.eat_kw("NS"):
            base = "ns"
        elif self.eat_kw("DATABASE") or self.eat_kw("DB"):
            base = "db"
        else:
            raise self.error("expected ROOT, NAMESPACE or DATABASE")
        self.expect_kw("TYPE")
        args: dict = {
            "name": name, "base": base, "if_not_exists": ine, "overwrite": ow,
            "access_type": None, "signup": None, "signin": None,
            "jwt_alg": "HS512", "jwt_key": None, "jwt_url": None,
            "authenticate": None, "token_duration": 3600 * 10**9,
            "session_duration": None, "comment": None,
        }
        if self.eat_kw("JWT"):
            args["access_type"] = "jwt"
            self._access_jwt_tail(args)
        elif self.eat_kw("RECORD"):
            args["access_type"] = "record"
            while True:
                if self.eat_kw("SIGNUP"):
                    args["signup"] = self.parse_expr()
                elif self.eat_kw("SIGNIN"):
                    args["signin"] = self.parse_expr()
                elif self.eat_kw("AUTHENTICATE"):
                    args["authenticate"] = self.parse_expr()
                elif self.eat_kw("WITH"):
                    self.expect_kw("JWT")
                    self._access_jwt_tail(args)
                else:
                    break
        elif self.eat_kw("BEARER"):
            args["access_type"] = "bearer"
            args["bearer_subject"] = "user"
            if self.eat_kw("FOR"):
                if self.eat_kw("USER"):
                    args["bearer_subject"] = "user"
                elif self.eat_kw("RECORD"):
                    args["bearer_subject"] = "record"
                else:
                    raise self.error("expected USER or RECORD")
        else:
            raise self.error("expected JWT, RECORD or BEARER")
        while True:
            if self.eat_kw("DURATION"):
                while self.eat_kw("FOR"):
                    if self.eat_kw("TOKEN"):
                        args["token_duration"] = self._duration().nanos
                    elif self.eat_kw("GRANT"):
                        if self.eat_kw("NONE"):
                            args["grant_duration"] = None
                        else:
                            args["grant_duration"] = self._duration().nanos
                    elif self.eat_kw("SESSION"):
                        if self.eat_kw("NONE"):
                            args["session_duration"] = None
                        else:
                            args["session_duration"] = self._duration().nanos
                    self.eat_op(",")
            elif self.eat_kw("AUTHENTICATE"):
                args["authenticate"] = self.parse_expr()
            elif self.is_kw("COMMENT"):
                args["comment"] = self._comment_clause()
            else:
                break
        return S.DefineStatement("access", **args)

    def _access_jwt_tail(self, args: dict) -> None:
        while True:
            if self.eat_kw("ALGORITHM"):
                args["jwt_alg"] = self.ident("algorithm").upper()
            elif self.eat_kw("KEY"):
                args["jwt_key"] = self.next().value
            elif self.eat_kw("URL"):
                args["jwt_url"] = self.next().value
            elif self.eat_kw("ISSUER"):
                self.expect_kw("KEY")
                args["jwt_issuer_key"] = self.next().value
            else:
                break

    def _define_model(self) -> S.Statement:
        ine, ow = self._if_not_exists()
        self.expect_kw("ML")
        self.expect_op("::")
        name = self.ident("model name")
        while self.eat_op("::"):
            name += "::" + self.ident("model name")
        version = ""
        if self.eat_op("<"):
            parts = [str(self.next().value)]
            while self.eat_op("."):
                parts.append(str(self.next().value))
            version = ".".join(parts)
            self.expect_op(">")
        perms = self._permissions_clause()
        comment = self._comment_clause()
        return S.DefineStatement(
            "model", name=name, version=version, if_not_exists=ine,
            overwrite=ow, permissions=perms, comment=comment,
        )

    # ---------------------------------------------------------- REMOVE
    def _stmt_remove(self) -> S.Statement:
        self.next()
        kinds = {
            "NAMESPACE": "namespace", "NS": "namespace",
            "DATABASE": "database", "DB": "database",
            "TABLE": "table", "FIELD": "field", "INDEX": "index",
            "EVENT": "event", "ANALYZER": "analyzer", "FUNCTION": "function",
            "PARAM": "param", "USER": "user", "ACCESS": "access",
            "MODEL": "model",
        }
        t = self.peek()
        if t.kind != "IDENT" or t.value.upper() not in kinds:
            raise self.error("unknown REMOVE kind")
        kind = kinds[self.next().value.upper()]
        if_exists = False
        if self.eat_kw("IF"):
            self.expect_kw("EXISTS")
            if_exists = True
        if kind == "function":
            self.expect_kw("FN")
            self.expect_op("::")
            name = self.ident("function name")
            while self.eat_op("::"):
                name += "::" + self.ident("function name")
        elif kind == "model":
            self.expect_kw("ML")
            self.expect_op("::")
            name = self.ident("model name")
            model_version = ""
            if self.eat_op("<"):
                v = [str(self.next().value)]
                while self.eat_op("."):
                    v.append(str(self.next().value))
                model_version = ".".join(v)
                self.expect_op(">")
        elif kind == "param":
            t2 = self.next()
            if t2.kind != "PARAM":
                raise self.error("expected $param", t2)
            name = t2.value
        else:
            name = self.ident("name")
        table = None
        level = None
        if kind == "model":
            table = model_version  # version rides the table slot
        if kind in ("field", "index", "event") and self.eat_kw("ON"):
            self.eat_kw("TABLE")
            table = self.ident("table name")
        if kind in ("user", "access") and self.eat_kw("ON"):
            if self.eat_kw("ROOT"):
                level = "root"
            elif self.eat_kw("NAMESPACE") or self.eat_kw("NS"):
                level = "ns"
            elif self.eat_kw("DATABASE") or self.eat_kw("DB"):
                level = "db"
        return S.RemoveStatement(kind, name, table, if_exists, level)

    def _stmt_alter(self) -> S.Statement:
        self.next()
        self.expect_kw("TABLE")
        if_exists = False
        if self.eat_kw("IF"):
            self.expect_kw("EXISTS")
            if_exists = True
        name = self.ident("table name")
        args: dict = {}
        while True:
            if self.eat_kw("DROP"):
                args["drop"] = True
            elif self.eat_kw("SCHEMAFULL"):
                args["schemafull"] = True
            elif self.eat_kw("SCHEMALESS"):
                args["schemafull"] = False
            elif self.is_kw("PERMISSIONS"):
                args["permissions"] = self._permissions_clause()
            elif self.is_kw("COMMENT"):
                args["comment"] = self._comment_clause()
            else:
                break
        return S.AlterStatement("table", name, if_exists, **args)

    def _stmt_rebuild(self) -> S.Statement:
        self.next()
        self.expect_kw("INDEX")
        if_exists = False
        if self.eat_kw("IF"):
            self.expect_kw("EXISTS")
            if_exists = True
        name = self.ident("index name")
        self.expect_kw("ON")
        self.eat_kw("TABLE")
        tb = self.ident("table name")
        return S.RebuildStatement(name, tb, if_exists)

    def _stmt_access(self) -> S.Statement:
        self.next()
        name = self.ident("access name")
        base = None
        if self.eat_kw("ON"):
            if self.eat_kw("ROOT"):
                base = "root"
            elif self.eat_kw("NAMESPACE") or self.eat_kw("NS"):
                base = "ns"
            elif self.eat_kw("DATABASE") or self.eat_kw("DB"):
                base = "db"
        if self.eat_kw("GRANT"):
            args = {}
            if self.eat_kw("FOR"):
                if self.eat_kw("USER"):
                    args["user"] = self.ident("user name")
                elif self.eat_kw("RECORD"):
                    args["record"] = self.parse_expr()
            return S.AccessStatement(name, base, "grant", **args)
        if self.eat_kw("SHOW"):
            args = {}
            if self.eat_kw("GRANT"):
                args["grant"] = self.ident("grant id")
            elif self.eat_kw("WHERE"):
                args["cond"] = self.parse_expr()
            else:
                self.eat_kw("ALL")
            return S.AccessStatement(name, base, "show", **args)
        if self.eat_kw("REVOKE"):
            args = {}
            if self.eat_kw("GRANT"):
                args["grant"] = self.ident("grant id")
            elif self.eat_kw("WHERE"):
                args["cond"] = self.parse_expr()
            elif not self.eat_kw("ALL"):
                # revoking everything is destructive: make it explicit
                raise self.error("expected GRANT <id>, WHERE <cond> or ALL")
            return S.AccessStatement(name, base, "revoke", **args)
        if self.eat_kw("PURGE"):
            args = {"expired": False, "revoked": False}
            while True:
                if self.eat_kw("EXPIRED"):
                    args["expired"] = True
                elif self.eat_kw("REVOKED"):
                    args["revoked"] = True
                elif self.eat_op(","):
                    continue
                else:
                    break
            if not args["expired"] and not args["revoked"]:
                args["expired"] = args["revoked"] = True
            if self.eat_kw("FOR"):
                args["grace"] = self._duration().nanos
            return S.AccessStatement(name, base, "purge", **args)
        raise self.error("expected GRANT, SHOW, REVOKE or PURGE")

    # ------------------------------------------------------------- kinds
    def parse_kind(self) -> Kind:
        k = self._parse_single_kind()
        if self.is_op("|"):
            kinds = [k]
            while self.eat_op("|"):
                kinds.append(self._parse_single_kind())
            return Kind("either", kinds)
        return k

    def _parse_single_kind(self) -> Kind:
        t = self.peek()
        if t.kind in ("NUMBER", "STRING", "DURATION") or (
            t.kind == "IDENT" and t.value.upper() in ("TRUE", "FALSE")
        ):
            self.next()
            if t.kind == "IDENT":
                return Kind("literal", [t.value.upper() == "TRUE"])
            return Kind("literal", [t.value])
        name = self.ident("type name").lower()
        if name == "option":
            self.expect_op("<")
            inner = self.parse_kind()
            self.expect_op(">")
            return Kind("option", [inner])
        if name in ("array", "set"):
            if self.eat_op("<"):
                inner = self.parse_kind()
                size = None
                if self.eat_op(","):
                    size = self.expect_int("an array size")
                self.expect_op(">")
                return Kind(name, [inner], size)
            return Kind(name)
        if name == "record":
            tables = []
            if self.eat_op("<"):
                tables.append(self.ident("table name"))
                while self.eat_op("|"):
                    tables.append(self.ident("table name"))
                self.expect_op(">")
            return Kind("record", tables)
        if name == "geometry":
            kinds = []
            if self.eat_op("<"):
                kinds.append(self.ident("geometry kind"))
                while self.eat_op("|"):
                    kinds.append(self.ident("geometry kind"))
                self.expect_op(">")
            return Kind("geometry", kinds)
        if name == "function":
            return Kind("function")
        return Kind(name)

    # ------------------------------------------------------------- idioms
    def parse_plain_idiom(self) -> P.Idiom:
        """Idiom without operators: a.b[0].c, used in SET/ORDER/GROUP..."""
        parts: List[P.Part] = []
        t = self.peek()
        if t.kind == "PARAM":
            self.next()
            parts.append(P.PStart(A.Param(t.value)))
        elif t.kind == "IDENT":
            self.next()
            parts.append(P.PField(t.value))
        elif t.kind == "NUMBER":
            self.next()
            parts.append(P.PField(str(t.value)))
        elif t.kind == "STRING":
            self.next()
            parts.append(P.PField(t.value))
        else:
            raise self.error("expected field path")
        self._idiom_tail(parts, graph=True)
        return P.Idiom(parts)

    def _idiom_tail(self, parts: List[P.Part], graph: bool = True) -> None:
        while True:
            if self.eat_op("."):
                if self.eat_op("*"):
                    parts.append(P.PAll())
                    continue
                if self.is_op("{"):
                    self.next()
                    fields: List[Tuple[str, Optional[List[P.Part]]]] = []
                    while not self.is_op("}"):
                        fname = self.ident("field name")
                        if self.eat_op(":"):
                            sub: List[P.Part] = [P.PField(self.ident("field"))]
                            self._idiom_tail(sub, graph=False)
                            fields.append((fname, sub))
                        else:
                            fields.append((fname, None))
                        if not self.eat_op(","):
                            break
                    self.expect_op("}")
                    parts.append(P.PDestructure(fields))
                    continue
                name = self.ident("field name")
                if self.is_op("("):
                    self.next()
                    args = []
                    while not self.is_op(")"):
                        args.append(self.parse_expr())
                        if not self.eat_op(","):
                            break
                    self.expect_op(")")
                    parts.append(P.PMethod(name, args))
                else:
                    parts.append(P.PField(name))
                continue
            if self.eat_op("["):
                if self.eat_op("*"):
                    self.expect_op("]")
                    parts.append(P.PAll())
                elif self.eat_op("$"):
                    self.expect_op("]")
                    parts.append(P.PLast())
                elif self.is_kw("WHERE"):
                    self.next()
                    cond = self.parse_expr()
                    self.expect_op("]")
                    parts.append(P.PWhere(cond))
                elif self.is_op("?"):
                    self.next()
                    cond = self.parse_expr()
                    self.expect_op("]")
                    parts.append(P.PWhere(cond))
                else:
                    e = self.parse_expr()
                    self.expect_op("]")
                    if isinstance(e, A.Literal) and isinstance(e.value, int):
                        parts.append(P.PIndex(e.value))
                    else:
                        parts.append(P.PValue(e))
                continue
            if self.is_op("?") and self.peek(1).kind == "OP" and self.peek(1).value == ".":
                self.next()
                parts.append(P.POptional())
                continue
            if graph and not self._no_graph and (
                self.is_op("->") or self.is_op("<-") or self.is_op("<->")
            ):
                parts.append(self._graph_part())
                continue
            if self.is_op("{") and self._recursion_ahead():
                parts.append(self._recurse_part())
                continue
            if self.eat_op(".."):
                # flatten operator `…` is typed as '..' + '.'? skip
                parts.append(P.PFlatten())
                continue
            return

    def _recursion_ahead(self) -> bool:
        # `{1..3}` or `{..}` directly in a path
        j = self.i + 1
        t = self.toks[j]
        if t.kind == "NUMBER":
            t2 = self.toks[j + 1]
            return t2.kind == "OP" and t2.value in ("..", "}")
        return t.kind == "OP" and t.value == ".."

    def _recurse_part(self) -> P.PRecurse:
        self.expect_op("{")
        mn, mx = 1, None
        if self.peek().kind == "NUMBER":
            mn = self.next().value
        if self.eat_op(".."):
            if self.peek().kind == "NUMBER":
                mx = self.next().value
        else:
            mx = mn
        self.expect_op("}")
        sub: List[P.Part] = []
        self._idiom_tail(sub, graph=True)
        return P.PRecurse(mn, mx, sub)

    def _graph_part(self) -> P.PGraph:
        t = self.next()
        dir_ = {"->": "out", "<-": "in", "<->": "both"}[t.value]
        if self.eat_op("?"):
            return P.PGraph(dir_, [])
        if self.eat_op("("):
            what = []
            cond = None
            alias = None
            if self.eat_op("?"):
                pass
            else:
                what.append(self.ident("edge table"))
                while self.eat_op(","):
                    what.append(self.ident("edge table"))
            if self.eat_kw("WHERE"):
                cond = self.parse_expr()
            if self.eat_kw("AS"):
                alias = self.parse_plain_idiom()
            self.expect_op(")")
            return P.PGraph(dir_, what, cond, alias)
        name = self.ident("edge table")
        return P.PGraph(dir_, [name])

    # ------------------------------------------------------------- exprs
    def parse_expr(self, min_bp: int = 0) -> A.Expr:
        # explicit nesting bound: each level spans several Python frames, so
        # pathological inputs (fuzzed `((((...`) exhaust the C stack — a hard
        # crash — long before sys.setrecursionlimit raises RecursionError
        self._depth += 1
        if self._depth > _MAX_PARSE_DEPTH:
            self._depth -= 1
            raise self.error("query is too deeply nested")
        try:
            return self._parse_expr_bp(min_bp)
        finally:
            self._depth -= 1

    def _parse_expr_bp(self, min_bp: int) -> A.Expr:
        lhs = self._parse_prefix()
        while True:
            t = self.peek()
            op = None
            if t.kind == "OP":
                if t.value == "<|":
                    lhs = self._knn_tail(lhs)
                    continue
                if t.value == "@":
                    lhs = self._matches_tail(lhs)
                    continue
                if t.value in _BP:
                    op = t.value
            elif t.kind == "IDENT":
                kw = t.value.upper()
                if kw == "NOT" and self.peek(1).kind == "IDENT" and self.peek(1).value.upper() in ("IN", "INSIDE"):
                    op = "NOT IN"
                elif kw in _BP:
                    op = kw
            if op is None:
                return lhs
            lbp, rbp = _BP.get(op, (40, 41))
            if lbp < min_bp:
                return lhs
            # consume
            if op == "NOT IN":
                self.next()
                self.next()
            else:
                self.next()
            if op == "IS":
                negate = self.eat_kw("NOT")
                rhs = self.parse_expr(rbp)
                lhs = A.BinaryOp("!=" if negate else "==", lhs, rhs)
                continue
            if op == "..":
                # range expression: lhs..[=]rhs
                end_incl = self.eat_op("=")
                if self._range_end_ahead():
                    rhs: Any = A.Literal(NONE)
                else:
                    rhs = self.parse_expr(rbp)
                lhs = A.RangeLit(lhs, rhs, True, end_incl)
                continue
            rhs = self.parse_expr(rbp)
            lhs = A.BinaryOp(op, lhs, rhs)

    def _range_end_ahead(self) -> bool:
        t = self.peek()
        return t.kind == "EOF" or (
            t.kind == "OP" and t.value in (")", "]", "}", ",", ";")
        )

    def _knn_tail(self, lhs: A.Expr) -> A.Expr:
        self.expect_op("<|")
        k = self.expect_int("a kNN k")
        ef = None
        dist = None
        if self.eat_op(","):
            t = self.next()
            if t.kind == "NUMBER":
                try:
                    ef = int(t.value)
                except (OverflowError, ValueError):
                    raise self.error("expected a kNN ef", t)
            else:
                dist = str(t.value).lower()
                if dist == "minkowski":
                    dist += f":{self.next().value}"
        self.expect_op("|>")
        rhs = self.parse_expr(45)
        return A.KnnOp(lhs, rhs, k, ef, dist)

    def _matches_tail(self, lhs: A.Expr) -> A.Expr:
        self.expect_op("@")
        ref = None
        if self.peek().kind == "NUMBER":
            ref = self.expect_int("a match ref")
        self.expect_op("@")
        rhs = self.parse_expr(45)
        return A.MatchesOp(lhs, rhs, ref)

    def _literal_methods(self, lit: A.Expr) -> A.Expr:
        """Allow method calls directly on literals (`'abc'.len()`,
        `5.is_int()`, `1w.days()` — reference idiom method dispatch)."""
        if self.is_op(".") and self.peek(1).kind == "IDENT" and self.is_op("(", 2):
            parts: List[P.Part] = [P.PStart(lit)]
            self._idiom_tail(parts, graph=False)
            return P.Idiom(parts)
        return lit

    def _parse_prefix(self) -> A.Expr:
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            return self._literal_methods(A.Literal(t.value))
        if t.kind == "STRING":
            self.next()
            # record-id strings: "person:1" auto-parse? (reference keeps string)
            return self._literal_methods(A.Literal(t.value))
        if t.kind == "DURATION":
            self.next()
            return self._literal_methods(A.Literal(t.value))
        if t.kind == "DATETIME":
            self.next()
            return self._literal_methods(A.Literal(t.value))
        if t.kind == "UUID":
            self.next()
            return self._literal_methods(A.Literal(t.value))
        if t.kind == "BYTES":
            self.next()
            return self._literal_methods(A.Literal(t.value))
        if t.kind == "PARAM":
            self.next()
            parts: List[P.Part] = [P.PStart(A.Param(t.value))]
            self._idiom_tail(parts)
            if len(parts) == 1:
                expr: A.Expr = A.Param(t.value)
            else:
                expr = P.Idiom(parts)
            if self.is_op("("):
                return self._closure_call(expr)
            return expr
        if t.kind == "OP":
            v = t.value
            if v == "-" or v == "+":
                self.next()
                return A.UnaryOp(v, self.parse_expr(65))
            if v == "!":
                self.next()
                if self.eat_op("!"):
                    return A.UnaryOp("!!", self.parse_expr(65))
                return A.UnaryOp("!", self.parse_expr(65))
            if v == "(":
                return self._paren_or_subquery()
            if v == "[":
                self.next()
                items = []
                while not self.is_op("]"):
                    items.append(self.parse_expr())
                    if not self.eat_op(","):
                        break
                self.expect_op("]")
                arr = A.ArrayLit(items)
                parts2: List[P.Part] = [P.PStart(arr)]
                self._idiom_tail(parts2)
                if len(parts2) > 1:
                    return P.Idiom(parts2)
                return arr
            if v == "{":
                return self._object_or_block()
            if v == "<":
                return self._angle_prefix()
            if v == "<-" or v == "<->":
                # graph idiom starting from current doc
                parts3: List[P.Part] = []
                self._idiom_tail(parts3)
                return P.Idiom(parts3)
            if v == "->":
                parts4: List[P.Part] = []
                self._idiom_tail(parts4)
                return P.Idiom(parts4)
            if v == "/":
                return self._regex_literal()
            if v == "|":
                return self._mock_or_closure()
            if v == "..":
                # open-beginning range ..end
                self.next()
                end_incl = self.eat_op("=")
                if self._range_end_ahead():
                    return A.RangeLit(A.Literal(NONE), A.Literal(NONE), True, end_incl)
                rhs = self.parse_expr(51)
                return A.RangeLit(A.Literal(NONE), rhs, True, end_incl)
            if v == "$":
                self.next()
                return A.Param("")
            if v == "*":
                self.next()
                return A.Literal("*")
        if t.kind == "IDENT":
            return self._ident_prefix()
        raise self.error(f"unexpected token {t.value!r}")

    def _closure_call(self, target: A.Expr) -> A.Expr:
        self.expect_op("(")
        args = []
        while not self.is_op(")"):
            args.append(self.parse_expr())
            if not self.eat_op(","):
                break
        self.expect_op(")")
        return A.ClosureCall(target, args)

    def _regex_literal(self) -> A.Expr:
        # lex manually from the raw text: /pattern/
        start_tok = self.next()  # consume '/'
        text = self.text
        j = start_tok.pos + 1
        pat = []
        while j < len(text):
            c = text[j]
            if c == "\\" and j + 1 < len(text):
                pat.append(text[j : j + 2])
                j += 2
                continue
            if c == "/":
                break
            pat.append(c)
            j += 1
        else:
            raise self.error("unterminated regex")
        # re-lex remainder
        from .lexer import Lexer

        sub = Lexer(text[j + 1 :])
        toks = sub.lex()
        offset = j + 1
        self.toks = self.toks[: self.i] + [
            Token(k, v, p + offset) for k, v, p in toks
        ]
        return A.RegexLit("".join(pat))

    def _mock_or_closure(self) -> A.Expr:
        self.next()  # consume |
        if self.peek().kind == "IDENT" and self.is_op(":", 1):
            tb = self.ident("table name")
            self.expect_op(":")
            n1 = self.expect_int("a number")
            if self.eat_op(".."):
                n2 = self.expect_int("a number")
                self.expect_op("|")
                return A.MockExpr(tb, None, (n1, n2))
            self.expect_op("|")
            return A.MockExpr(tb, n1, None)
        # closure |$a: int, $b| body
        params: List[Tuple[str, Optional[Kind]]] = []
        while not self.is_op("|"):
            t = self.next()
            if t.kind != "PARAM":
                raise self.error("expected $param in closure", t)
            kind = None
            if self.eat_op(":"):
                # single kind only: `|` would be ambiguous with the closing pipe
                kind = self._parse_single_kind()
            params.append((t.value, kind))
            if not self.eat_op(","):
                break
        self.expect_op("|")
        returns = None
        if self.eat_op("->"):
            returns = self.parse_kind()
        body = self.parse_block_expr()
        return A.ClosureLit(params, returns, body)

    def _paren_or_subquery(self) -> A.Expr:
        self.expect_op("(")
        t = self.peek()
        if t.kind == "IDENT" and t.value.upper() in (
            "SELECT", "CREATE", "UPDATE", "UPSERT", "DELETE", "RELATE",
            "INSERT", "DEFINE", "REMOVE", "IF", "RETURN",
        ):
            stmt = self.parse_statement()
            self.expect_op(")")
            sq = A.Subquery(stmt)
            parts: List[P.Part] = [P.PStart(sq)]
            self._idiom_tail(parts)
            if len(parts) > 1:
                return P.Idiom(parts)
            return sq
        # geometry point? (1.5, 2.5)
        e = self.parse_expr()
        if self.eat_op(","):
            e2 = self.parse_expr()
            self.expect_op(")")
            from surrealdb_tpu.sql.value import Geometry

            return A.FunctionCall("__point__", [e, e2])
        self.expect_op(")")
        parts = [P.PStart(A.Subquery(_ExprStatement(e)) if isinstance(e, (S.Statement,)) else e)]
        self._idiom_tail(parts)
        if len(parts) > 1:
            return P.Idiom(parts)
        return e

    def _object_or_block(self) -> A.Expr:
        # lookahead: '{' '}' or '{' (IDENT|STRING) ':' => object, else block
        if self.is_op("}", 1):
            self.next()
            self.next()
            return A.ObjectLit([])
        t1, t2 = self.peek(1), self.peek(2)
        is_obj = (
            t1.kind in ("IDENT", "STRING", "NUMBER")
            and t2.kind == "OP"
            and t2.value == ":"
        )
        if is_obj:
            self.next()
            pairs: List[Tuple[str, A.Expr]] = []
            while not self.is_op("}"):
                kt = self.next()
                if kt.kind not in ("IDENT", "STRING", "NUMBER"):
                    raise self.error("expected object key", kt)
                key = str(kt.value)
                self.expect_op(":")
                pairs.append((key, self.parse_expr()))
                if not self.eat_op(","):
                    break
            self.expect_op("}")
            obj = A.ObjectLit(pairs)
            parts: List[P.Part] = [P.PStart(obj)]
            self._idiom_tail(parts)
            if len(parts) > 1:
                return P.Idiom(parts)
            return obj
        return self.parse_block_expr()

    def parse_block_expr(self) -> A.Expr:
        """{ stmts } block, or a single expression."""
        if self.is_op("{"):
            self.next()
            stmts: List[S.Statement] = []
            while True:
                while self.eat_op(";"):
                    pass
                if self.is_op("}"):
                    break
                stmts.append(self.parse_statement())
                if self.is_op("}"):
                    break
                if not self.eat_op(";"):
                    break
            self.expect_op("}")
            return A.Block(stmts)
        # single statement (e.g. FOR body must be block; IF allows expr)
        t = self.peek()
        if t.kind == "IDENT" and t.value.upper() in _STMT_KEYWORDS and t.value.upper() not in ("IF",):
            return A.Subquery(self.parse_statement())
        return self.parse_expr()

    def _angle_prefix(self) -> A.Expr:
        """<kind> cast, <future>, <-graph handled elsewhere."""
        self.next()  # consume <
        if self.eat_kw("FUTURE"):
            self.expect_op(">")
            body = self.parse_block_expr()
            if isinstance(body, A.Block) and len(body.stmts) == 1 and isinstance(
                body.stmts[0], _ExprStatement
            ):
                return A.FutureLit(body.stmts[0].expr)
            return A.FutureLit(body)
        kind = self.parse_kind()
        self.expect_op(">")
        return A.Cast(kind, self.parse_expr(65))

    def _ident_prefix(self) -> A.Expr:
        t = self.next()
        name = t.value
        up = name.upper()
        if up == "TRUE":
            return A.Literal(True)
        if up == "FALSE":
            return A.Literal(False)
        if up == "NULL":
            return A.Literal(Null)
        if up == "NONE":
            return A.Literal(NONE)
        if up == "NAN":
            return A.Literal(float("nan"))
        if up == "NOT":
            return A.UnaryOp("!", self.parse_expr(45))
        if up in ("SELECT", "CREATE", "UPDATE", "UPSERT", "DELETE", "RELATE", "INSERT"):
            self.i -= 1
            return A.Subquery(self.parse_statement())
        if up == "IF":
            self.i -= 1
            self.next()
            return A.Subquery(self._parse_if_tail())
        # fn::name(...)
        if up == "FN" and self.is_op("::"):
            self.next()
            fname = self.ident("function name")
            while self.eat_op("::"):
                fname += "::" + self.ident("function name")
            self.expect_op("(")
            args = []
            while not self.is_op(")"):
                args.append(self.parse_expr())
                if not self.eat_op(","):
                    break
            self.expect_op(")")
            return A.CustomFunctionCall(fname, args)
        # ml::name<ver>(...)
        if up == "ML" and self.is_op("::"):
            self.next()
            mname = self.ident("model name")
            while self.eat_op("::"):
                mname += "::" + self.ident("model name")
            version = ""
            if self.eat_op("<"):
                parts = [str(self.next().value)]
                while self.eat_op("."):
                    parts.append(str(self.next().value))
                version = ".".join(parts)
                self.expect_op(">")
            self.expect_op("(")
            args = []
            while not self.is_op(")"):
                args.append(self.parse_expr())
                if not self.eat_op(","):
                    break
            self.expect_op(")")
            return A.ModelCall(mname, version, args)
        # namespaced function / constant: math::pi, array::len(...)
        if self.is_op("::"):
            full = name
            while self.eat_op("::"):
                nxt = self.peek()
                if nxt.kind == "IDENT" or nxt.kind == "NUMBER":
                    self.next()
                    full += "::" + str(nxt.value)
                else:
                    raise self.error("expected name after ::")
            if self.is_op("("):
                self.next()
                args = []
                while not self.is_op(")"):
                    args.append(self.parse_expr())
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
                call = A.FunctionCall(full, args)
                parts5: List[P.Part] = [P.PStart(call)]
                self._idiom_tail(parts5)
                if len(parts5) > 1:
                    return P.Idiom(parts5)
                return call
            if full.lower() in A.Constant._VALUES:
                return A.Constant(full.lower())
            raise self.error(f"unknown constant {full}")
        # embedded script block: function(args) { js }  (the lexer emits a
        # SCRIPT token right after the closing paren in exactly this shape)
        if name == "function" and self.is_op("("):
            j = self.i + 1
            depth = 1
            while j < len(self.toks) and depth:
                t = self.toks[j]
                if t.kind == "OP" and t.value == "(":
                    depth += 1
                elif t.kind == "OP" and t.value == ")":
                    depth -= 1
                j += 1
            if j < len(self.toks) and self.toks[j].kind == "SCRIPT":
                self.next()  # (
                args = []
                while not self.is_op(")"):
                    args.append(self.parse_expr())
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
                src_tok = self.next()
                return A.ScriptCall(src_tok.value, args)
        # plain function call: count(), rand(), type::of...
        if self.is_op("("):
            self.next()
            args = []
            while not self.is_op(")"):
                args.append(self.parse_expr())
                if not self.eat_op(","):
                    break
            self.expect_op(")")
            call = A.FunctionCall(name.lower(), args)
            parts6: List[P.Part] = [P.PStart(call)]
            self._idiom_tail(parts6)
            if len(parts6) > 1:
                return P.Idiom(parts6)
            return call
        # record id: ident:...
        if self.is_op(":"):
            nt = self.peek(1)
            if nt.kind in ("NUMBER", "IDENT", "STRING", "UUID", "DURATION") or (
                nt.kind == "OP" and nt.value in ("[", "{", "..", "⟨", "-", "|")
            ):
                self.next()  # consume :
                thing = self._thing_tail(name)
                parts7: List[P.Part] = [P.PStart(thing)]
                self._idiom_tail(parts7)
                if len(parts7) > 1:
                    return P.Idiom(parts7)
                return thing
        # plain idiom: field path / table name
        parts8: List[P.Part] = [P.PField(name)]
        self._idiom_tail(parts8)
        return P.Idiom(parts8)

    def _thing_tail(self, tb: str) -> A.Expr:
        """After `tb:` parse the id part (may be a range)."""
        t = self.peek()
        beg_incl = True
        # range forms: tb:beg..end, tb:beg>..end, tb:..end
        def id_atom() -> Any:
            t = self.peek()
            if t.kind in ("NUMBER", "DURATION"):
                # flexible record ids (reference syn/parser/thing.rs:251
                # flexible_record_id): digit-leading alphanumeric ids like
                # `likes:8abc2`, `t:1h30x`, `t:5h44m5f4x` lex as a run of
                # NUMBER/DURATION/IDENT tokens; merge the whole adjacent
                # [A-Za-z0-9_]+ source run back into one string id and
                # resync the token stream past every token inside it
                nxt = self.peek(1)
                if nxt.kind in ("IDENT", "NUMBER", "DURATION") and not any(
                    c.isspace() for c in self.text[t.pos : nxt.pos]
                ):
                    end = t.pos
                    while end < len(self.text) and (
                        self.text[end].isalnum() or self.text[end] == "_"
                    ):
                        end += 1
                    while self.peek().kind != "EOF" and self.peek().pos < end:
                        self.next()
                    # a token straddling the run boundary (e.g. `8e+2`)
                    # cannot merge cleanly into an id
                    gap = self.text[end : self.peek().pos]
                    if gap.strip():
                        raise self.error("invalid record id", t)
                    return self.text[t.pos : end]
                if t.kind == "DURATION":
                    # a bare duration-shaped id (`t:1h`) is a string id
                    self.next()
                    end = t.pos
                    while end < len(self.text) and (
                        self.text[end].isalnum() or self.text[end] == "_"
                    ):
                        end += 1
                    return self.text[t.pos : end]
                self.next()
                if isinstance(t.value, float):
                    # `t:8e2` — number-shaped but alnum text is a string id
                    # (reference Digits + identifier-chars → Id::String)
                    raw = self.text[t.pos : self.peek().pos].rstrip()
                    if raw and all(c.isalnum() or c == "_" for c in raw):
                        return raw
                    raise self.error("record id must be an integer", t)
                return t.value
            if t.kind == "IDENT":
                self.next()
                return t.value
            if t.kind == "STRING":
                self.next()
                return t.value
            if t.kind == "UUID":
                self.next()
                return t.value
            if t.kind == "OP" and t.value == "-":
                self.next()
                nt = self.next()
                if nt.kind != "NUMBER" or isinstance(nt.value, float):
                    raise self.error("record id must be an integer", nt)
                return -nt.value
            if t.kind == "OP" and t.value == "[":
                self.next()
                items = []
                while not self.is_op("]"):
                    items.append(self.parse_expr())
                    if not self.eat_op(","):
                        break
                self.expect_op("]")
                return A.ArrayLit(items)
            if t.kind == "OP" and t.value == "{":
                e = self._object_or_block()
                return e
            if t.kind == "OP" and t.value == "|":
                raise self.error("unexpected | in record id")
            raise self.error("expected record id")

        if self.is_op(".."):
            self.next()
            end_incl = self.eat_op("=")
            if self._range_end_ahead():
                rng = A.RangeLit(A.Literal(NONE), A.Literal(NONE), True, end_incl)
            else:
                end = id_atom()
                rng = A.RangeLit(
                    A.Literal(NONE),
                    end if isinstance(end, A.Expr) else A.Literal(end),
                    True,
                    end_incl,
                )
            return A.ThingLit(tb, rng)
        atom = id_atom()
        if self.is_op("..") or (self.is_op(">") and self.is_op("..", 1)):
            if self.eat_op(">"):
                beg_incl = False
            self.next()  # consume ..
            end_incl = self.eat_op("=")
            if self._range_end_ahead():
                end: Any = A.Literal(NONE)
            else:
                e2 = id_atom()
                end = e2 if isinstance(e2, A.Expr) else A.Literal(e2)
            rng = A.RangeLit(
                atom if isinstance(atom, A.Expr) else A.Literal(atom),
                end,
                beg_incl,
                end_incl,
            )
            return A.ThingLit(tb, rng)
        if isinstance(atom, A.Expr):
            return A.ThingLit(tb, atom)
        return A.Literal(Thing(tb, atom))


class _ExprStatement(S.Statement):
    """A bare expression used in statement position."""

    __slots__ = ("expr",)

    def __init__(self, expr: A.Expr):
        self.expr = expr

    def compute(self, ctx):
        return self.expr.compute(ctx)

    def writeable(self):
        return self.expr.writeable()

    def __repr__(self):
        return repr(self.expr)


# ------------------------------------------------------------------ entries
def parse_query(text: str) -> S.Query:
    try:
        return Parser(text).parse_query()
    except RecursionError:
        # pathological nesting (the reference bounds computation depth the
        # same way, cnf MAX_COMPUTATION_DEPTH) — report a clean parse error
        raise ParseError("query is too deeply nested") from None


# The plan cache's token vocabulary (dbs/plan_cache.py). SIGNATURE kinds
# are every token whose VALUE the statement fingerprint erases or folds
# (stats._normalize): literals erase to "?", params to "$?", keyword
# identifiers case-fold. Two same-fingerprint texts can therefore differ
# ONLY at these positions — operators are preserved verbatim by the
# fingerprint, so they can never differ. BINDABLE kinds are the subset
# whose converted value is exactly what an ast.Literal node would hold,
# i.e. the ones a cached template can re-bind per execution; the rest
# (idents, param names, regexes) must match the template verbatim.
SIGNATURE_TOKEN_KINDS = frozenset(
    {"IDENT", "PARAM", "NUMBER", "STRING", "DURATION",
     "DATETIME", "UUID", "BYTES", "REGEX", "SCRIPT"}
)
BINDABLE_TOKEN_KINDS = frozenset(
    {"NUMBER", "STRING", "DURATION", "DATETIME", "UUID", "BYTES"}
)


def lex_literal_slots(text: str) -> Optional[Tuple[Tuple[str, ...], Tuple[Any, ...]]]:
    """The plan cache's lex-only front (dbs/plan_cache.py): tokenize one
    statement and return the (kinds, values) sequence of its SIGNATURE
    tokens in source order, or None when the text does not lex. A warm
    serve of a new same-fingerprint text pays THIS instead of a full
    parse — bindable values slot into the cached template AST, everything
    else is compared verbatim against the template's signature."""
    try:
        tokens = lex(text)
    except (ParseError, RecursionError):
        return None
    kinds: List[str] = []
    values: List[Any] = []
    for t in tokens:
        if t.kind == "EOF":
            break
        if t.kind in SIGNATURE_TOKEN_KINDS:
            kinds.append(t.kind)
            values.append(t.value)
    return tuple(kinds), tuple(values)


def parse_expr_text(text: str) -> A.Expr:
    try:
        p = Parser(text)
        e = p.parse_expr()
    except RecursionError:
        raise ParseError("expression is too deeply nested") from None
    if p.peek().kind != "EOF":
        raise p.error("unexpected trailing input")
    return e


def parse_thing_text(text: str) -> Thing:
    p = Parser(text)
    e = p.parse_expr()
    if isinstance(e, A.Literal) and isinstance(e.value, Thing):
        return e.value
    if isinstance(e, A.ThingLit) and not isinstance(e.id, A.Expr):
        return Thing(e.tb, e.id)
    if isinstance(e, A.ThingLit):
        v = e.compute(None)  # literal-only ids compute without ctx
        if isinstance(v, Thing):
            return v
    raise ParseError(f"not a record id: {text!r}")


def parse_kind_text(text: str) -> Kind:
    return Parser(text).parse_kind()
