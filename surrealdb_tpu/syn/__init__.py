"""Parser entry points (reference: core/src/syn/mod.rs:45-299)."""

from .parser import (
    Parser,
    parse_expr_text as parse_value,
    parse_kind_text as parse_kind,
    parse_query,
    parse_thing_text as parse_thing,
)

__all__ = ["Parser", "parse_query", "parse_value", "parse_thing", "parse_kind"]
