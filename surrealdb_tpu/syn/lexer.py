"""SurrealQL lexer.

Role of the reference's byte-level lexer with compound tokens (reference:
core/src/syn/lexer/). Produces a flat token list; keywords are recognised
contextually by the parser (SurrealQL keywords are case-insensitive and may
appear as identifiers in most positions).
"""

from __future__ import annotations

import re
from typing import List, NamedTuple, Optional

from surrealdb_tpu.err import ParseError


class Token(NamedTuple):
    kind: str  # IDENT NUMBER STRING DURATION DATETIME UUID BYTES PARAM OP REGEX EOF
    value: object
    pos: int


# Multi-char operators, longest first.
_OPERATORS = [
    "<|",  # knn open  <|k,ef|>
    "|>",
    "?:",
    "??",
    "==",
    "!=",
    "?=",
    "*=",
    "!~",
    "*~",
    "<=",
    ">=",
    "+=",
    "-=",
    "+?=",
    "->",
    "<->",
    "<-",
    "**",
    "..",
    "::",
    "⟨",
    "&&",
    "||",
    "≤",
    "≥",
    "×",
    "÷",
]
_SINGLE = set("+-*/%=<>!&|,.;:()[]{}@?~^$")

_NUM_RE = re.compile(
    r"(?:\d[\d_]*\.\d[\d_]*(?:[eE][+-]?\d+)?|\d[\d_]*[eE][+-]?\d+|\d[\d_]*)(f|dec)?"
)
_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_DUR_UNIT_RE = re.compile(r"(ns|us|µs|ms|s|m|h|d|w|y)")
_WS_RE = re.compile(r"[ \t\r\n]+")


class Lexer:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0
        self.n = len(text)
        self.tokens: List[Token] = []

    def error(self, msg: str, pos: Optional[int] = None) -> ParseError:
        p = self.pos if pos is None else pos
        line = self.text.count("\n", 0, p) + 1
        col = p - (self.text.rfind("\n", 0, p) + 1) + 1
        return ParseError(msg, p, line, col)

    def lex(self) -> List[Token]:
        while True:
            self._skip_ws_comments()
            if self.pos >= self.n:
                self.tokens.append(Token("EOF", None, self.pos))
                return self.tokens
            start = self.pos
            c = self.text[self.pos]
            if c.isdigit():
                self._lex_number_or_duration()
            elif c == '"' or c == "'":
                self.tokens.append(Token("STRING", self._lex_string(c), start))
            elif c in ("s", "r", "d", "u", "b") and self.pos + 1 < self.n and self.text[
                self.pos + 1
            ] in ("'", '"'):
                self._lex_prefixed_string(c)
            elif c.isalpha() or c == "_":
                m = _IDENT_RE.match(self.text, self.pos)
                self.pos = m.end()
                self.tokens.append(Token("IDENT", m.group(), start))
                if m.group() == "function":
                    # `function(<sql args>) { <js> }` — capture the raw JS
                    # body as one SCRIPT token (reference: syn lexes JS
                    # compound tokens for sql::Script)
                    self._maybe_lex_script()
            elif c == "`":
                # backtick-quoted identifier
                end = self.text.find("`", self.pos + 1)
                if end < 0:
                    raise self.error("unterminated ` identifier")
                self.tokens.append(Token("IDENT", self.text[self.pos + 1 : end], start))
                self.pos = end + 1
            elif c == "⟨":
                # scan with \⟩ escape support (escape_ident emits it)
                j = self.pos + 1
                out = []
                while j < self.n and self.text[j] != "⟩":
                    if self.text[j] == "\\" and j + 1 < self.n and self.text[j + 1] == "⟩":
                        out.append("⟩")
                        j += 2
                    else:
                        out.append(self.text[j])
                        j += 1
                if j >= self.n:
                    raise self.error("unterminated ⟨ identifier")
                self.tokens.append(Token("IDENT", "".join(out), start))
                self.pos = j + 1
            elif c == "$":
                m = _IDENT_RE.match(self.text, self.pos + 1)
                if m:
                    self.pos = m.end()
                    self.tokens.append(Token("PARAM", m.group(), start))
                else:
                    self.pos += 1
                    self.tokens.append(Token("OP", "$", start))
            else:
                self._lex_operator()
        # unreachable

    # ------------------------------------------------------------------ ws
    def _skip_ws_comments(self) -> None:
        while self.pos < self.n:
            m = _WS_RE.match(self.text, self.pos)
            if m:
                self.pos = m.end()
                continue
            if (
                self.text.startswith("--", self.pos)
                or self.text.startswith("//", self.pos)
                or self.text.startswith("#", self.pos)
            ):
                nl = self.text.find("\n", self.pos)
                self.pos = self.n if nl < 0 else nl + 1
                continue
            if self.text.startswith("/*", self.pos):
                end = self.text.find("*/", self.pos + 2)
                if end < 0:
                    raise self.error("unterminated block comment")
                self.pos = end + 2
                continue
            return

    # ------------------------------------------------------------------ script
    def _maybe_lex_script(self) -> None:
        """After an IDENT `function`: if the source reads `( args ) {`, lex
        the SurrealQL arg list via a sub-lexer and capture the JS block as
        one SCRIPT token; otherwise leave the stream untouched."""
        ws = _WS_RE.match(self.text, self.pos)
        p = ws.end() if ws else self.pos
        if p >= self.n or self.text[p] != "(":
            return
        depth, j = 0, p
        while j < self.n:
            ch = self.text[j]
            if ch in "\"'":
                j = self._skip_quoted(j)
                continue
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    break
            j += 1
        if j >= self.n:
            return
        ws2 = _WS_RE.match(self.text, j + 1)
        q = ws2.end() if ws2 else j + 1
        if q >= self.n or self.text[q] != "{":
            return
        self.tokens.append(Token("OP", "(", p))
        sub = Lexer(self.text[p + 1 : j])
        for t in sub.lex():
            if t.kind == "EOF":
                break
            self.tokens.append(Token(t.kind, t.value, p + 1 + t.pos))
        self.tokens.append(Token("OP", ")", j))
        end = self._scan_js_block(q)
        self.tokens.append(Token("SCRIPT", self.text[q + 1 : end], q))
        self.pos = end + 1

    def _skip_quoted(self, i: int) -> int:
        """Index just past a quoted SQL string starting at i."""
        quote = self.text[i]
        j = i + 1
        while j < self.n:
            if self.text[j] == "\\":
                j += 2
                continue
            if self.text[j] == quote:
                return j + 1
            j += 1
        raise self.error("unterminated string", i)

    def _scan_js_block(self, open_pos: int) -> int:
        """Index of the `}` matching the `{` at open_pos, respecting JS
        strings, template literals, and comments."""
        depth = 0
        j = open_pos
        while j < self.n:
            ch = self.text[j]
            if ch in "\"'":
                j = self._skip_quoted(j)
                continue
            if ch == "`":
                j += 1
                while j < self.n and self.text[j] != "`":
                    if self.text[j] == "\\":
                        j += 2
                        continue
                    # ${ expr } inside a template nests normal JS braces
                    if self.text.startswith("${", j):
                        d2 = 1
                        j += 2
                        while j < self.n and d2:
                            if self.text[j] == "{":
                                d2 += 1
                            elif self.text[j] == "}":
                                d2 -= 1
                            j += 1
                        continue
                    j += 1
                j += 1
                continue
            if self.text.startswith("//", j):
                nl = self.text.find("\n", j)
                j = self.n if nl < 0 else nl + 1
                continue
            if self.text.startswith("/*", j):
                e = self.text.find("*/", j + 2)
                if e < 0:
                    raise self.error("unterminated comment in script", j)
                j = e + 2
                continue
            if ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    return j
            j += 1
        raise self.error("unterminated script block", open_pos)

    # ------------------------------------------------------------------ num
    def _lex_number_or_duration(self) -> None:
        start = self.pos
        m = _NUM_RE.match(self.text, self.pos)
        if not m:
            raise self.error("invalid number")
        raw = m.group().replace("_", "")
        self.pos = m.end()
        # duration? only if a PLAIN INTEGER part is followed directly by a
        # unit — float/scientific forms like `2e6y` are a number + ident run
        # (a flexible record id), never a duration
        um = _DUR_UNIT_RE.match(self.text, self.pos)
        if um and m.group(1) is None and raw.isdigit() and not (
            um.group() in ("s", "m", "h", "d", "w", "y")
            and self.pos + len(um.group()) < self.n
            and (self.text[self.pos + len(um.group())].isalnum() or self.text[self.pos + len(um.group())] == "_")
            and not self.text[self.pos + len(um.group())].isdigit()
        ):
            # accumulate number-unit pairs: 1h30m
            total_text = raw + um.group()
            self.pos += len(um.group())
            while self.pos < self.n and self.text[self.pos].isdigit():
                m2 = _NUM_RE.match(self.text, self.pos)
                u2 = (
                    m2
                    and m2.group().replace("_", "").isdigit()
                    and _DUR_UNIT_RE.match(self.text, m2.end())
                )
                if not (m2 and u2):
                    break
                total_text += m2.group().replace("_", "") + u2.group()
                self.pos = u2.end()
            from surrealdb_tpu.sql.value import Duration

            self.tokens.append(Token("DURATION", Duration.parse(total_text), start))
            return
        suffix = m.group(1)
        if suffix == "dec":
            from decimal import Decimal

            self.tokens.append(Token("NUMBER", Decimal(raw[:-3]), start))
        elif suffix == "f":
            self.tokens.append(Token("NUMBER", float(raw[:-1]), start))
        elif "." in raw or "e" in raw or "E" in raw:
            self.tokens.append(Token("NUMBER", float(raw), start))
        else:
            self.tokens.append(Token("NUMBER", int(raw), start))

    # ------------------------------------------------------------------ str
    def _lex_string(self, quote: str, raw: bool = False) -> str:
        # assumes text[pos] == quote
        out = []
        i = self.pos + 1
        while i < self.n:
            c = self.text[i]
            if c == "\\":
                if i + 1 >= self.n:
                    raise self.error("unterminated string", self.pos)
                e = self.text[i + 1]
                if raw:
                    # raw strings: only the quote escape collapses
                    out.append(e if e == quote else "\\" + e)
                    i += 2
                    continue
                mapping = {
                    "n": "\n",
                    "t": "\t",
                    "r": "\r",
                    "\\": "\\",
                    "/": "/",
                    '"': '"',
                    "'": "'",
                    "b": "\b",
                    "f": "\f",
                    "0": "\0",
                }
                if e == "u":
                    if self.text[i + 2] == "{":
                        end = self.text.find("}", i + 3)
                        out.append(chr(int(self.text[i + 3 : end], 16)))
                        i = end + 1
                        continue
                    out.append(chr(int(self.text[i + 2 : i + 6], 16)))
                    i += 6
                    continue
                # unknown escapes keep the backslash verbatim
                out.append(mapping[e] if e in mapping else "\\" + e)
                i += 2
                continue
            if c == quote:
                self.pos = i + 1
                return "".join(out)
            out.append(c)
            i += 1
        raise self.error("unterminated string", self.pos)

    def _lex_prefixed_string(self, prefix: str) -> None:
        start = self.pos
        self.pos += 1  # skip prefix char
        body = self._lex_string(self.text[self.pos], raw=(prefix == "r"))
        if prefix == "s":
            self.tokens.append(Token("STRING", body, start))
        elif prefix == "r":
            self.tokens.append(Token("STRING", body, start))
        elif prefix == "d":
            from surrealdb_tpu.sql.value import Datetime

            try:
                self.tokens.append(Token("DATETIME", Datetime.parse(body), start))
            except ValueError as e:
                raise self.error(f"invalid datetime: {e}", start)
        elif prefix == "u":
            import uuid as _uuid

            from surrealdb_tpu.sql.value import Uuid

            try:
                self.tokens.append(Token("UUID", Uuid(_uuid.UUID(body)), start))
            except ValueError as e:
                raise self.error(f"invalid uuid: {e}", start)
        elif prefix == "b":
            try:
                self.tokens.append(Token("BYTES", bytes.fromhex(body), start))
            except ValueError as e:
                raise self.error(f"invalid bytes literal: {e}", start)

    # ------------------------------------------------------------------ ops
    def _lex_operator(self) -> None:
        start = self.pos
        for op in _OPERATORS:
            if self.text.startswith(op, self.pos):
                self.pos += len(op)
                self.tokens.append(Token("OP", op, start))
                return
        c = self.text[self.pos]
        if c in _SINGLE:
            self.pos += 1
            self.tokens.append(Token("OP", c, start))
            return
        raise self.error(f"unexpected character {c!r}")


def lex(text: str) -> List[Token]:
    return Lexer(text).lex()
